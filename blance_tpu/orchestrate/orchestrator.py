"""Rebalance orchestrator: executes map-to-map transitions cluster-wide.

Reimplements the reference's control plane (reference: /root/reference/
orchestrate.go:80-763) on asyncio: one mover task per node, a supplier task
running broadcast rounds, per-node concurrency limits, app-controlled move
prioritization, pause/resume/stop, and a blocking progress stream.

Round structure (orchestrate.go:509-618): each round groups every
partition's *current* move by destination node, spawns one feeder per node
with that node's best k moves, and the FIRST successful feed interrupts all
other feeders so availability is recomputed — this keeps the whole cluster's
choices fresh as work completes.  A feeder that finds its batch already
in-flight waits on that move instead of double-feeding
(orchestrate.go:622-696).

The app's assign_partitions callback is the only data plane — the
orchestrator never moves bytes itself, so it is transport-agnostic by
construction (orchestrate.go:148-152).
"""

from __future__ import annotations

import asyncio
import inspect
import random
import warnings as _warnings
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Awaitable, Callable, Optional, Union

from ..core.types import Partition, PartitionMap, PartitionModel
from ..moves.calc import calc_partition_moves
from ..obs import get_recorder
from ..plan.greedy import sort_state_names
from .csp import Chan, select, GET, PUT
from .health import HealthTracker
# The app-weight ordering lives in the sched package now (ISSUE 12:
# LegacyWeightOrder behind the scheduler interface); re-exported here
# unchanged so every existing import site keeps working.
from .sched.policy import (
    MOVE_OP_WEIGHT,
    BoundScheduler,
    LegacyWeightOrder,
    SchedulerPolicy,
    lowest_weight_partition_move_for_node,
)

if TYPE_CHECKING:  # annotation-only; obs.slo must not import us back
    from ..obs.slo import MoveObserver

__all__ = [
    "ErrorStopped",
    "ErrorInterrupt",
    "MissingMoverError",
    "MoveFailure",
    "MoveTimeoutError",
    "NodeQuarantinedError",
    "Orchestrator",
    "OrchestratorOptions",
    "OrchestratorProgress",
    "PartitionMove",
    "NextMoves",
    "MOVE_OP_WEIGHT",
    "lowest_weight_partition_move_for_node",
    "orchestrate_moves",
]


class StoppedError(Exception):
    """The operation was stopped (reference orchestrate.go:18)."""


class InterruptError(Exception):
    """The operation was interrupted by a broadcast (orchestrate.go:21)."""


# Sentinel singletons, compared by identity like the reference's error vars.
ErrorStopped = StoppedError("stopped")
ErrorInterrupt = InterruptError("interrupt")


class MoveTimeoutError(Exception):
    """An assign callback exceeded OrchestratorOptions.move_timeout_s."""

    def __init__(self, node: str, timeout_s: float) -> None:
        super().__init__(f"assign_partitions for node {node!r} exceeded "
                         f"move deadline {timeout_s}s")
        self.node = node
        self.timeout_s = timeout_s


class NodeQuarantinedError(Exception):
    """A batch was released unexecuted: its node is quarantined."""

    def __init__(self, node: str) -> None:
        super().__init__(f"node {node!r} is quarantined")
        self.node = node


class MissingMoverError(Exception):
    """A move targets a node outside nodes_all — no mover will ever
    serve it (reference orchestrate.go:667 nil-channel semantics)."""

    def __init__(self, node: str) -> None:
        super().__init__(f"move targets node {node!r} which has no mover "
                         f"(not in nodes_all)")
        self.node = node


@dataclass(eq=False)  # exception identity semantics; stays hashable
class MoveFailure(Exception):
    """One partition move that fault-tolerant orchestration gave up on.

    Replaces the bare exception of the legacy path when the options
    enable deadlines/retries/quarantine: carries exactly which (node,
    partition, state, op) failed, how many attempts were burned, and the
    last underlying cause (app exception, MoveTimeoutError,
    NodeQuarantinedError, or MissingMoverError).  Flows through
    progress.errors and ``Orchestrator.move_failures()``; the recovery
    replan (rebalance_async) consumes it."""

    node: str
    partition: str
    state: str
    op: str
    attempts: int
    cause: object

    def __post_init__(self) -> None:
        Exception.__init__(
            self, f"move failed: partition={self.partition!r} "
            f"node={self.node!r} state={self.state!r} op={self.op!r} "
            f"attempts={self.attempts} cause={self.cause!r}")


@dataclass
class OrchestratorOptions:
    """Advanced config (orchestrate.go:110-115 + scale extensions)."""

    # <= 0 is treated as 1 (orchestrate.go:484-487).
    max_concurrent_partition_moves_per_node: int = 1
    favor_min_nodes: bool = False

    # -- fault-tolerance extensions (not in the reference; ALL unset =>
    #    the reference's exact failure semantics: an app error aborts the
    #    orchestration, a hung callback stalls its mover, a moverless
    #    target blocks until stop).  Setting any of them turns a
    #    timed-out or retry-exhausted move into a structured MoveFailure
    #    recorded in progress.errors, and the orchestration continues
    #    with the remaining partitions. --
    # Per-move deadline for ASYNC assign callbacks (a sync callback
    # blocks the loop and cannot be preempted); a breach counts as a
    # failed attempt with a MoveTimeoutError cause.
    move_timeout_s: Optional[float] = None
    # Failed attempts are retried up to this many times with exponential
    # backoff: base * 2^attempt * (1 + jitter * u), u drawn from a
    # Random(retry_seed) so schedules are reproducible.
    max_retries: int = 0
    backoff_base_s: float = 0.05
    backoff_jitter: float = 0.25
    retry_seed: int = 0
    # Circuit breaker: this many CONSECUTIVE failed attempts quarantine a
    # node (0 disables).  Queued batches for a quarantined node are
    # released immediately as MoveFailures; after probe_after_s one probe
    # batch at a time is admitted and a success re-opens the node
    # (orchestrate/health.py).
    quarantine_after: int = 0
    probe_after_s: float = 1.0
    # Externally-owned HealthTracker (e.g. carried across the recovery
    # rounds of one rebalance); when set, quarantine_after/probe_after_s
    # are ignored in favor of the tracker's own thresholds.
    health: Optional[HealthTracker] = None

    @property
    def fault_tolerant(self) -> bool:
        """True when any fault-tolerance option deviates from defaults."""
        return (self.move_timeout_s is not None or self.max_retries > 0
                or self.quarantine_after > 0 or self.health is not None)

    # -- scale extensions (not in the reference) --
    # True (reference semantics, orchestrate.go:566-580): the first
    # successful feed each round interrupts all other feeders, so
    # availability is recomputed after every accepted batch — freshest
    # choices, but rounds commit ~one batch each.  False: every node's
    # feeder completes its feed before the next round, so a round commits
    # up to len(nodes) batches — the throughput mode for 10k+ partition
    # rebalances, where per-batch recomputes would be quadratic.
    interrupt_on_first_feed: bool = True
    # Compute the up-front per-partition move plans with the batched
    # on-device diff (moves/batch.py) instead of the per-partition host
    # loop.  Identical op lists; worthwhile from ~10k partitions up.
    device_diff: bool = False
    # Move-ordering policy (orchestrate/sched, docs/SCHEDULER.md).
    # None = the reference's app-weight order (LegacyWeightOrder), the
    # pinned default.  CriticalPathScheduler turns the flat move list
    # into a critical-path-prioritized schedule minimizing rebalance
    # MAKESPAN on calibrated per-(node, op) costs — the final map and
    # move set stay bit-identical, only the order (and the clock)
    # changes.  Mutually exclusive with a custom find_move callback.
    scheduler: Optional[SchedulerPolicy] = None
    # -- durability extension (docs/DURABILITY.md) --
    # Fenced epoch for the journal directory this orchestration serves
    # (durability/epoch.py EpochFence; duck-typed `current`/`valid` so
    # this layer needs no durability import).  The orchestrator captures
    # the epoch ONCE at construction and re-checks it at every batch
    # completion: a callback resolving after a crash recovery bumped the
    # fence is a zombie — its outcome is rejected and counted
    # (durability.stale_epoch_rejections), never applied to the achieved
    # map or shown to observers.  None disables fencing (the default:
    # one-shot rebalances have no journal to protect).
    epoch_fence: Optional[Any] = None


@dataclass
class OrchestratorProgress:
    """Monotonic progress counters + errors, streamed as whole snapshots
    (orchestrate.go:119-141)."""

    errors: list[Exception] = field(default_factory=list)

    tot_stop: int = 0
    tot_pause_new_assignments: int = 0
    tot_resume_new_assignments: int = 0
    tot_run_mover: int = 0
    tot_run_mover_done: int = 0
    tot_run_mover_done_err: int = 0
    tot_mover_loop: int = 0
    tot_mover_assign_partition: int = 0
    tot_mover_assign_partition_ok: int = 0
    tot_mover_assign_partition_err: int = 0
    tot_run_supply_moves_loop: int = 0
    tot_run_supply_moves_loop_done: int = 0
    tot_run_supply_moves_feeding: int = 0
    tot_run_supply_moves_feeding_done: int = 0
    tot_run_supply_moves_done: int = 0
    tot_run_supply_moves_done_err: int = 0
    tot_run_supply_moves_pause: int = 0
    tot_run_supply_moves_resume: int = 0
    tot_progress_close: int = 0

    # -- fault-tolerance counters (always 0 in legacy mode) --
    tot_mover_assign_partition_retry: int = 0
    tot_mover_assign_partition_timeout: int = 0
    tot_mover_quarantine_reject: int = 0
    tot_quarantine_trips: int = 0
    tot_move_failures: int = 0
    # Supersede cancellations (Orchestrator.cancel): a newer cluster
    # delta invalidated this transition mid-flight and the control loop
    # resumed from achieved_map() instead of letting it finish.
    tot_cancel: int = 0

    def snapshot(self) -> "OrchestratorProgress":
        # One snapshot per progress event: a shallow __dict__ copy is
        # ~4x cheaper than dataclasses.replace (which re-runs __init__
        # over all 20 fields); only `errors` needs its own list.
        new = object.__new__(type(self))  # keep subclass snapshots typed
        new.__dict__.update(self.__dict__)
        new.errors = list(self.errors)
        return new


@dataclass(frozen=True)
class PartitionMove:
    """A state change/op for one partition on one node (orchestrate.go:162-172)."""

    partition: str
    node: str
    state: str  # "" means removal
    op: str  # "add" | "del" | "promote" | "demote"


class NextMoves:
    """Cursor over one partition's immutable move sequence
    (orchestrate.go:198-214)."""

    __slots__ = ("partition", "next", "moves", "next_done_ch", "failed_at")

    def __init__(self, partition: str, moves: list[PartitionMove]) -> None:
        self.partition = partition
        self.next = 0  # index of the next available move
        self.moves = moves
        # Non-None while the current move is in flight; == the feeding
        # request's done channel.
        self.next_done_ch: Optional[Chan] = None
        # Fault-tolerant mode: index of the move that failed when this
        # partition was abandoned (its remaining moves are skipped;
        # ``next`` jumps to len(moves) so availability drops it).  None
        # while healthy — and always None in legacy mode.
        self.failed_at: Optional[int] = None


class _PartitionMoveReq:
    """A batch of moves for one node + completion channel (orchestrate.go:220-223).

    ``t_created`` stamps the feeder's creation time (on the Recorder's
    clock, so virtual time under DeterministicLoop) so the mover that
    eventually dequeues the batch can attribute queue/concurrency wait
    separately from callback execution (the ``orchestrate.move`` span)."""

    __slots__ = ("partition_moves", "done_ch", "t_created")

    def __init__(self, partition_moves: list[PartitionMove], done_ch: Chan,
                 t_created: float) -> None:
        self.partition_moves = partition_moves
        self.done_ch = done_ch
        self.t_created = t_created


AssignPartitionsFunc = Callable[..., Union[Optional[Exception], Awaitable]]
FindMoveFunc = Callable[[str, list[PartitionMove]], int]


class Orchestrator:
    """Runtime state of one orchestrate_moves() run (orchestrate.go:80-106)."""

    def __init__(
        self,
        model: PartitionModel,
        options: OrchestratorOptions,
        nodes_all: list[str],
        beg_map: PartitionMap,
        end_map: PartitionMap,
        assign_partitions: AssignPartitionsFunc,
        find_move: Optional[FindMoveFunc],
        map_partition_to_next_moves: dict[str, NextMoves],
        move_observers: "tuple[MoveObserver, ...]" = (),
    ) -> None:
        self.model = model
        self.options = options
        self.nodes_all = nodes_all
        self.beg_map = beg_map
        self.end_map = end_map
        self._assign_partitions = assign_partitions
        self._find_move = find_move or lowest_weight_partition_move_for_node

        self._progress_ch = Chan()
        self._map_node_to_req_ch = {node: Chan() for node in nodes_all}

        self._stop_ch: Optional[Chan] = Chan()
        self._pause_ch: Optional[Chan] = None
        self._progress = OrchestratorProgress()
        self._map_partition_to_next_moves = map_partition_to_next_moves

        self._tasks: list["asyncio.Task[object]"] = []
        # Monotone spawn counter: gives every orchestration task a
        # stable, human-readable name (mover/supplier/feeder + ordinal).
        # The schedule explorer (testing/sched.py) keys its step labels
        # — and therefore schedule signatures — off task names, so this
        # is the hook that makes explorer traces legible.
        self._spawn_seq = 0
        # Every progress counter is mirrored into the obs Recorder
        # (orchestrate.tot_*) as it increments, so one sink sees the
        # progress stream, the planner spans, and the move lifecycle
        # together.  Bound once: a rebalance reports to the recorder that
        # was installed when it started.  The recorder's clock is also
        # the orchestrator's ONLY time source (queue waits, exec
        # timings), so an injected virtual clock covers the whole move
        # lifecycle deterministically.
        self._rec = get_recorder()
        # Move observers (obs.slo.MoveObserver): notified synchronously
        # after every batch outcome with (node, moves, ok, now) — the
        # SLO plane's incremental achieved-map delta feed.  Immutable
        # after init; callbacks must be plain sync code.
        self._observers: "tuple[MoveObserver, ...]" = tuple(move_observers)

        # Move-ordering policy (orchestrate/sched): every run binds one
        # — LegacyWeightOrder when options leave the default, which
        # selects byte-identically to the pre-extraction app-weight
        # code.  A custom find_move callback and a scheduler are
        # mutually exclusive: both claim the same decision.
        policy = options.scheduler
        if policy is not None and \
                self._find_move is not lowest_weight_partition_move_for_node:
            raise ValueError(
                "OrchestratorOptions.scheduler and a custom find_move "
                "callback are mutually exclusive — both decide which "
                "move a node runs next")
        if policy is None:
            policy = LegacyWeightOrder()
        self.sched: BoundScheduler = policy.bind(
            nodes_all, map_partition_to_next_moves,
            options.max_concurrent_partition_moves_per_node, self._rec)
        if self.sched.observes_batches:
            self._observers = self._observers + (self.sched,)

        # -- fault tolerance (all inert when options keep the defaults) --
        self._ft = options.fault_tolerant
        self.failures: list[MoveFailure] = []
        if options.health is not None:
            self.health: Optional[HealthTracker] = options.health
        elif options.quarantine_after > 0:
            # The breaker shares the recorder's clock so quarantine
            # dwell/exposure accounting and the SLO gauges agree (and
            # all follow virtual time when a test injects one);
            # perf_counter and monotonic have unrelated epochs, so
            # mixing them would corrupt exposure arithmetic.
            self.health = HealthTracker(
                threshold=options.quarantine_after,
                probe_after_s=options.probe_after_s,
                clock=self._rec.now)
        else:
            self.health = None
        self._retry_rng = random.Random(options.retry_seed)
        # Fenced epoch, captured ONCE: if a crash recovery bumps the
        # fence mid-flight, every later completion in this run reads as
        # stale and is rejected (see _mover_loop).
        self._epoch = (options.epoch_fence.current
                       if options.epoch_fence is not None else 0)
        self._missing_mover_warned: set[str] = set()
        # Set by the supplier AFTER the progress channel closes: the
        # whole wind-down (movers exited, feeders resolved) is complete.
        # The supersede path (RebalanceController) awaits it so a
        # cancelled transition leaves no orphan tasks behind.
        self._drained = asyncio.Event()

    # -- public control surface ---------------------------------------------

    def progress_ch(self) -> Chan:
        """Progress snapshot stream; MUST be drained until close or the
        orchestration wedges (documented requirement, orchestrate.go:230-232).
        Iterate with ``async for``."""
        return self._progress_ch

    def stop(self) -> None:
        """Idempotent async stop; the progress channel eventually closes
        (orchestrate.go:342-350)."""
        if self._stop_ch is not None:
            self._bump_sync("tot_stop")
            self._stop_ch.close()
            self._stop_ch = None

    def cancel(self) -> None:
        """Supersede: stop the transition because a newer cluster delta
        invalidated its end map.  Semantically a stop() — in-flight
        callbacks finish or fail like any stop — but counted separately
        (``tot_cancel``) so dashboards can tell an operator stop from a
        control-loop supersede.  Resume from ``achieved_map()`` once
        :meth:`wait_drained` returns.  Idempotent."""
        if self._stop_ch is not None:
            self._bump_sync("tot_cancel")
        self.stop()

    async def wait_drained(self) -> None:
        """Block until the orchestration has fully wound down — the
        supplier closed the progress stream after every mover exited.
        The progress channel must still be drained by its consumer (the
        documented requirement); this is the rendezvous for a SECOND
        party (the control loop's supersede path) that needs the
        wind-down without owning the drain."""
        await self._drained.wait()

    def pending_tasks(self) -> "list[asyncio.Task[object]]":
        """Orchestration tasks not yet finished — the no-orphan-tasks
        probe the supersede explorer scenario asserts empty after a
        cancel + wait_drained (a just-resolved mover may need one more
        loop tick to finalize)."""
        return [t for t in self._tasks if not t.done()]

    def pause_new_assignments(self) -> None:
        """Stop starting new assignments; in-flight moves finish.  Idempotent
        (orchestrate.go:367-375)."""
        if self._pause_ch is None:
            self._pause_ch = Chan()
            self._bump_sync("tot_pause_new_assignments")

    def resume_new_assignments(self) -> None:
        """Idempotent resume (orchestrate.go:379-388)."""
        if self._pause_ch is not None:
            self._bump_sync("tot_resume_new_assignments")
            self._pause_ch.close()
            self._pause_ch = None

    def visit_next_moves(
            self, cb: Callable[[dict[str, NextMoves]], None]) -> None:
        """Read access to the live move cursors, e.g. for UIs
        (orchestrate.go:395-399)."""
        cb(self._map_partition_to_next_moves)

    def move_failures(self) -> list[MoveFailure]:
        """Structured failures collected so far (fault-tolerant mode
        only; legacy mode aborts on the first error instead).  Complete
        once progress_ch() has closed."""
        return list(self.failures)

    def achieved_map(self) -> PartitionMap:
        """Reconstruct the map the cluster actually reached: beg_map with
        every SUCCESSFULLY executed move applied, per partition, up to
        its cursor (an abandoned partition counts its moves up to the
        one that failed — a failed batch is assumed not applied).

        This is the honest ``current_map`` for a failure-aware recovery
        replan; call after progress_ch() closes (mid-run it reflects the
        in-flight frontier, which is fine for dashboards but racy as a
        replan input)."""
        achieved: PartitionMap = {}
        for name, beg in self.beg_map.items():
            nbs = {s: list(ns) for s, ns in beg.nodes_by_state.items()}
            nm = self._map_partition_to_next_moves.get(name)
            upto = 0 if nm is None else (
                nm.failed_at if nm.failed_at is not None else nm.next)
            for mv in (nm.moves[:upto] if nm is not None else ()):
                for ns in nbs.values():
                    if mv.node in ns:
                        ns.remove(mv.node)
                if mv.state:  # "" = removal (the "del" op)
                    nbs.setdefault(mv.state, []).append(mv.node)
            achieved[name] = Partition(name, nbs)
        return achieved

    # -- internals -----------------------------------------------------------

    def _spawn(self, coro: Awaitable[object]) -> "asyncio.Task[object]":
        """Spawn an orchestration task with its exception OBSERVED.

        A bare ``ensure_future`` whose result nobody awaits is the
        asyncio bug class the static suite flags (analysis/asyncio_lint
        ASY101): the Task can be garbage-collected mid-run, and an
        escaped exception surfaces only as a destructor warning long
        after the orchestration wedged.  Every mover/supplier/feeder
        goes through here instead: the task is retained in
        ``self._tasks`` (pruned as tasks finish, so thousands of feeder
        rounds don't accumulate) and a done-callback retrieves its
        exception — escaped ones (loop bugs; app errors are converted to
        move errors before they can escape) are surfaced as a
        UserWarning plus an ``orchestrate.task_exceptions`` counter
        instead of vanishing."""
        task = asyncio.ensure_future(coro)
        if isinstance(task, asyncio.Task):
            self._spawn_seq += 1
            task.set_name(
                f"{getattr(coro, '__qualname__', 'orchestrate-task')}"
                f"-{self._spawn_seq}")
        self._tasks = [t for t in self._tasks if not t.done()]
        self._tasks.append(task)

        def _observe(t: "asyncio.Task[object]") -> None:
            if t.cancelled():
                return
            exc = t.exception()  # marks the exception retrieved
            if exc is not None:
                self._rec.count("orchestrate.task_exceptions")
                _warnings.warn(
                    f"blance_tpu orchestrate: internal task died with "
                    f"{type(exc).__name__}: {exc}", UserWarning)

        task.add_done_callback(_observe)
        return task

    def _start(self, stop_ch: Chan) -> None:
        run_mover_done_ch = Chan()
        for node in self.nodes_all:
            self._spawn(self._run_mover(stop_ch, run_mover_done_ch, node))
        self._spawn(self._run_supply_moves(stop_ch, run_mover_done_ch))

    async def _update_progress(self, mutate: Callable[[], None]) -> None:
        """Apply a counter mutation and blocking-send a snapshot
        (orchestrate.go:735-745)."""
        mutate()
        await self._progress_ch.put(self._progress.snapshot())

    def _bump_sync(self, *names: str) -> None:
        """Increment progress counters, mirrored into the Recorder."""
        for name in names:
            setattr(self._progress, name, getattr(self._progress, name) + 1)
            self._rec.count("orchestrate." + name)

    async def _bump(self, *names: str) -> None:
        """_bump_sync + blocking progress snapshot — the one spelling every
        counter-only progress event goes through."""
        await self._update_progress(lambda: self._bump_sync(*names))

    async def _call_assign(
        self, stop_ch: Chan, node: str, partitions: list[str],
        states: list[str], ops: list[str],
    ) -> Optional[Exception]:
        """Invoke the app callback (sync or async); exceptions become the
        move's error.  With ``move_timeout_s`` set, an ASYNC callback
        that outlives the deadline is cancelled and the attempt fails
        with MoveTimeoutError (sync callbacks block the loop and cannot
        be preempted — use an async data plane for deadlines)."""
        timeout_s = self.options.move_timeout_s
        try:
            result = self._assign_partitions(stop_ch, node, partitions, states, ops)
            if inspect.isawaitable(result):
                if timeout_s is not None:
                    # The TimeoutError handler is scoped to wait_for ONLY,
                    # and a deadline breach is distinguished from the app
                    # RAISING TimeoutError itself (on 3.11+
                    # asyncio.TimeoutError IS builtin TimeoutError, e.g. a
                    # socket timeout) by whether wait_for cancelled the
                    # callback: only a breach does.  An app-raised timeout
                    # flows through as the app's error, never rebranded.
                    fut = asyncio.ensure_future(result)
                    try:
                        result = await asyncio.wait_for(fut, timeout_s)
                    except asyncio.TimeoutError as exc:
                        if not fut.cancelled():
                            return exc  # the app's own TimeoutError
                        self._rec.count("orchestrate.timeouts")
                        self._bump_sync("tot_mover_assign_partition_timeout")
                        return MoveTimeoutError(node, timeout_s)
                else:
                    result = await result
        except Exception as exc:  # app errors flow into progress.errors
            return exc
        return result if isinstance(result, Exception) else None

    async def _wait_or_stop(self, stop_ch: Chan, delay_s: float) -> bool:
        """Sleep ``delay_s``, aborting early when stop fires; True means
        the orchestration was stopped.  Backoff must never outlive
        stop(): a 30 s retry backoff on a dead node would otherwise hold
        the whole wind-down hostage."""
        if stop_ch.closed:
            return True
        getter = asyncio.ensure_future(stop_ch.get())
        done, _pending = await asyncio.wait({getter}, timeout=delay_s)
        if getter not in done:
            # csp.Chan tolerates cancelled waiters: close() skips
            # completed/cancelled futures instead of resolving them.
            getter.cancel()
            try:
                await getter
            except asyncio.CancelledError:
                pass
            # Eagerly drop the abandoned waiter: the stop channel is
            # shared by every mover, and one dead getter per expired
            # backoff would otherwise accumulate until close().
            stop_ch._gc()
        return stop_ch.closed

    async def _exec_with_retries(
        self, stop_ch: Chan, node: str, partitions: list[str],
        states: list[str], ops: list[str],
    ) -> tuple[Optional[Exception], int]:
        """One batch execution under the fault-tolerance policy: bounded
        retries with exponential backoff + deterministic jitter, per-
        attempt health reporting.  Returns (err, attempts); legacy mode
        (no FT options) is exactly one _call_assign."""
        opts = self.options
        max_attempts = 1 + (max(opts.max_retries, 0) if self._ft else 0)
        attempt = 0
        while True:
            attempt += 1
            err = await self._call_assign(stop_ch, node, partitions,
                                          states, ops)
            if err is None:
                if self.health is not None and \
                        self.health.record_success(node):
                    # The probe healed the node: its lanes rejoin the
                    # machine model (no-op for legacy order).
                    self.sched.on_heal(node)
                return None, attempt
            tripped = False
            if self.health is not None:
                tripped = self.health.record_failure(node)
                if tripped:
                    self._bump_sync("tot_quarantine_trips")
                    # Online reschedule: the node's lanes just left the
                    # machine model; the scheduler rebuilds priorities
                    # from the remaining DAG (no-op for legacy order).
                    self.sched.on_quarantine(node)
            if not self._ft or attempt >= max_attempts or tripped:
                return err, attempt
            delay = opts.backoff_base_s * (2.0 ** (attempt - 1))
            delay *= 1.0 + max(opts.backoff_jitter, 0.0) * \
                self._retry_rng.random()
            self._rec.count("orchestrate.retries")
            self._rec.observe("orchestrate.retry_backoff_s", delay)
            await self._bump("tot_mover_assign_partition_retry")
            if await self._wait_or_stop(stop_ch, delay):
                return err, attempt

    async def _run_mover(self, stop_ch: Chan, done_ch: Chan, node: str) -> None:
        await self._bump("tot_run_mover")
        err = await self._mover_loop(stop_ch, self._map_node_to_req_ch[node], node)
        await done_ch.put(err)

    async def _mover_loop(self, stop_ch: Chan, req_ch: Chan,
                          node: str) -> Optional[Exception]:
        """Receive batched move requests and run the assign callback
        synchronously per batch (orchestrate.go:426-480).

        Each dequeued batch becomes one ``orchestrate.move`` lifecycle span
        on the ``mover:<node>`` lane, starting at the feeder's request
        creation: an ``orchestrate.move.wait`` child (time spent queued
        behind this node's concurrency limit / rendezvous) and an
        ``orchestrate.move.exec`` child (the app callback), so per-node
        wait is attributable separately from mover execution.  Callback
        latency also lands in the ``orchestrate.move_latency_s`` histogram,
        once per partition move in the batch with the batch's exec time
        amortized across them (histogram sum = exec wall-clock)."""
        while True:
            await self._bump("tot_mover_loop")

            which, value = await select((GET, stop_ch), (GET, req_ch))
            if which == 0:
                return None
            req, ok = value
            if not ok:
                return None
            t_recv = self._rec.now()

            partitions = [pm.partition for pm in req.partition_moves]
            states = [pm.state for pm in req.partition_moves]
            ops = [pm.op for pm in req.partition_moves]

            # Circuit breaker: a quarantined node's queued batches are
            # released immediately as failures — no callback, no retry
            # budget — so a dead node's work drains instead of wedging.
            # A half-open probe admission executes normally; its outcome
            # heals or re-trips the node (orchestrate/health.py).
            admit = "ok"
            if self.health is not None:
                admit = self.health.admit(node)

            lane = f"mover:{node}"
            with self._rec.span(
                    "orchestrate.move", t_start=req.t_created, task=lane,
                    node=node, moves=len(req.partition_moves)) as mv:
                self._rec.record_span(
                    "orchestrate.move.wait", req.t_created, t_recv,
                    task=lane, node=node)

                if admit == "reject":
                    await self._bump("tot_mover_quarantine_reject")
                    err, attempts = NodeQuarantinedError(node), 0
                    mv.attrs["quarantined"] = True
                    mv.attrs["ok"] = False
                else:
                    await self._bump("tot_mover_assign_partition")

                    t_exec = self._rec.now()
                    with self._rec.span("orchestrate.move.exec", task=lane,
                                        node=node, ops=",".join(ops)):
                        err, attempts = await self._exec_with_retries(
                            stop_ch, node, partitions, states, ops)
                    exec_s = self._rec.now() - t_exec
                    mv.attrs["wait_s"] = t_recv - req.t_created
                    mv.attrs["exec_s"] = exec_s
                    mv.attrs["ok"] = err is None
                    if attempts > 1:
                        mv.attrs["attempts"] = attempts
                    # One observation per partition move, with the batch's
                    # callback time amortized across its moves — so the
                    # histogram's sum equals real exec wall-clock, not
                    # batch-size-weighted batch latency.
                    per_move_s = exec_s / max(len(req.partition_moves), 1)
                    for _ in req.partition_moves:
                        self._rec.observe("orchestrate.move_latency_s",
                                          per_move_s)

                    await self._bump(
                        "tot_mover_assign_partition_err" if err is not None
                        else "tot_mover_assign_partition_ok")

            # Epoch fencing (docs/DURABILITY.md): a completion observed
            # after a crash recovery bumped the journal's fence is a
            # ZOMBIE — this whole orchestrator predates the recovery.
            # The outcome is rejected and counted, never applied: no
            # observer sees it (the successor's journal/SLO view stays
            # the truth) and the error marks the cursor failed, so
            # achieved_map() never includes the move.
            fence = self.options.epoch_fence
            if fence is not None and not fence.valid(self._epoch):
                from ..durability.epoch import StaleEpochError
                self._rec.count("durability.stale_epoch_rejections")
                err = StaleEpochError(
                    f"move batch on node {node!r}", self._epoch,
                    fence.current)
            # SLO / cost-model hook: every batch outcome, success or
            # failure, with the recorder-clock timestamp.  Observers are
            # sync (no await): the placement-view update is atomic on
            # the loop, so concurrent movers cannot tear it.
            elif self._observers:
                t_done = self._rec.now()
                for observer in self._observers:
                    observer.on_batch(node, req.partition_moves,
                                      err is None, t_done)

            if err is not None and self._ft:
                # Structured failure per partition move in the batch; the
                # first one rides the done channel so waiting feeders can
                # abandon their cursors without aborting the round loop.
                err = await self._record_batch_failure(
                    node, req.partition_moves, attempts, err)

            if req.done_ch is not None:
                if err is not None:
                    await select((GET, stop_ch), (PUT, req.done_ch, err))
                req.done_ch.close()

    async def _record_batch_failure(
        self, node: str, partition_moves: list[PartitionMove],
        attempts: int, cause: object,
    ) -> MoveFailure:
        """Fold one failed batch into the structured failure history:
        one MoveFailure per partition move, appended to ``failures`` AND
        ``progress.errors`` (snapshot emitted once for the batch).
        Returns the first failure, the batch's representative error."""
        batch = [
            MoveFailure(node=node, partition=pm.partition, state=pm.state,
                        op=pm.op, attempts=attempts, cause=cause)
            for pm in partition_moves
        ]
        self.failures.extend(batch)

        def record():
            for f in batch:
                self._progress.errors.append(f)
                self._bump_sync("tot_move_failures")
                self._rec.count("orchestrate.move_failures")
        await self._update_progress(record)
        return batch[0]

    def _filter_next_plausible_moves_for_node(
        self, node: str, next_moves_arr: list[NextMoves]
    ) -> list[NextMoves]:
        """Pick up to max_concurrent best moves via the app's find_move
        (orchestrate.go:482-504)."""
        count = self.options.max_concurrent_partition_moves_per_node
        if count <= 0:
            count = 1
        count = min(count, len(next_moves_arr))

        arr = list(next_moves_arr)
        picked: list[NextMoves] = []
        while count > 0:
            i = self._find_next_moves(node, arr)
            picked.append(arr[i])
            count -= 1
            arr[i] = arr[-1]
            arr.pop()
        return picked

    def _find_next_moves(self, node: str, next_moves_arr: list[NextMoves]) -> int:
        """Ask the app which available move to do next (orchestrate.go:699-714)."""
        if self._find_move is lowest_weight_partition_move_for_node:
            # Scheduler path (default LegacyWeightOrder, or the policy
            # the options set): selection reads the live cursors
            # directly — the legacy bound hands each candidate's
            # op-bearing NodeStateOp straight to the weight rule, the
            # exact pre-extraction fast path (measured ~50% of
            # scheduler time at 8k partitions), and the critical-path
            # bound looks up (partition, cursor) upward ranks.
            return self.sched.select(node, next_moves_arr)
        moves = [
            PartitionMove(
                partition=nm.partition,
                node=nm.moves[nm.next].node,
                state=nm.moves[nm.next].state,
                op=nm.moves[nm.next].op,
            )
            for nm in next_moves_arr
        ]
        return self._find_move(node, moves)

    def _find_available_moves(self) -> dict[str, list[NextMoves]]:
        """Group each partition's current move by destination node
        (orchestrate.go:749-763)."""
        available: dict[str, list[NextMoves]] = {}
        for nm in self._map_partition_to_next_moves.values():
            if nm.next < len(nm.moves):
                available.setdefault(nm.moves[nm.next].node, []).append(nm)
        return available

    async def _wait_while_paused(self) -> None:
        """Block the supplier between rounds while paused, REVALIDATING
        ``self._pause_ch`` after every wake.

        The pre-fix spelling captured the channel once and waited on the
        capture: a pause→resume→pause cycle landing inside the
        pause-counter put (a blocking progress rendezvous) closed the
        captured channel and parked the NEW one — the wait returned
        immediately and the supplier fed a fresh round while the
        orchestrator was logically paused (RACE002, the stale-guard
        window analysis/race_lint.py flags; the committed schedule
        trace in tests/test_race_regressions.py replays the exact
        interleaving).  Re-reading the attribute after each wake closes
        the window.

        EVERY progress bump in here is itself a blocking rendezvous a
        consumer can act inside — including the resume bump — so the
        decisive ``_pause_ch is None`` check is the one made after the
        resume bump, with no suspension point between it and the
        return: a pause landing during any earlier await sends the
        supplier back around the outer loop (surfacing each cycle as a
        pause+resume counter pair — honest accounting, and the event
        traffic keeps a snapshot-driven consumer live while the
        supplier stays correctly parked)."""
        while True:
            await self._bump("tot_run_supply_moves_pause")
            while True:
                pause_ch = self._pause_ch
                if pause_ch is None:
                    break
                await pause_ch.get()
            await self._bump("tot_run_supply_moves_resume")
            if self._pause_ch is None:
                return

    async def _run_supply_moves(self, stop_ch: Chan, run_mover_done_ch: Chan) -> None:
        """The round loop (orchestrate.go:509-618)."""
        err_outer = None

        while err_outer is None:
            await self._bump("tot_run_supply_moves_loop")

            available = self._find_available_moves()
            pause_ch = self._pause_ch

            if not available:
                break

            # Pause blocks the whole supplier between rounds; Stop() while
            # paused requires a resume first (orchestrate.go:531-544).
            if pause_ch is not None:
                await self._wait_while_paused()

            broadcast_stop_ch = Chan()
            broadcast_done_ch = Chan()

            interrupt = self.options.interrupt_on_first_feed

            # A move can target a node with no mover (not in nodes_all); its
            # feeder blocks until stop/broadcast (reference orchestrate.go:667
            # nil-channel semantics).  In interrupt mode the first success
            # unblocks it every round.  In throughput mode broadcast closes
            # only after all feeders report, so a blocked feeder would
            # deadlock the round — skip moverless nodes instead, unless NO
            # node is feedable (then spawn the blocking feeders to reproduce
            # the reference's wedge-until-Stop rather than a busy spin).
            feed_nodes = available
            if not interrupt:
                feedable = {node: arr for node, arr in available.items()
                            if node in self._map_node_to_req_ch}
                if feedable:
                    feed_nodes = feedable

            for node, next_moves_arr in feed_nodes.items():
                picked = self._filter_next_plausible_moves_for_node(
                    node, next_moves_arr)
                self._spawn(self._run_supply_move(
                    stop_ch, node, picked, broadcast_stop_ch,
                    broadcast_done_ch))

            await self._bump("tot_run_supply_moves_feeding")

            # First successful feed interrupts the other feeders so the next
            # round recomputes availability (orchestrate.go:566-580); in
            # throughput mode every feeder finishes and a round commits up
            # to len(feed_nodes) batches.
            broadcast_stopped = False
            for _ in range(len(feed_nodes)):
                err, _ok = await broadcast_done_ch.get()
                if err is None and interrupt and not broadcast_stopped:
                    broadcast_stop_ch.close()
                    broadcast_stopped = True
                if isinstance(err, MoveFailure) and self._ft:
                    # Already recorded in progress.errors/failures; the
                    # partition was abandoned.  NOT fatal: the remaining
                    # partitions keep moving (legacy mode instead aborts
                    # on the first error, below).  A completed feed — even
                    # a failed one — still refreshes availability.
                    if interrupt and not broadcast_stopped:
                        broadcast_stop_ch.close()
                        broadcast_stopped = True
                    continue
                if err is not None and err is not ErrorInterrupt and err_outer is None:
                    err_outer = err

            await self._bump("tot_run_supply_moves_feeding_done")

            if not broadcast_stopped:
                broadcast_stop_ch.close()
            broadcast_done_ch.close()

        await self._bump("tot_run_supply_moves_loop_done")

        for req_ch in self._map_node_to_req_ch.values():
            req_ch.close()

        def count_done():
            self._bump_sync("tot_run_supply_moves_done")
            if err_outer is not None and err_outer is not ErrorStopped:
                self._progress.errors.append(err_outer)
                self._bump_sync("tot_run_supply_moves_done_err")
                self._rec.count("orchestrate.errors")
        await self._update_progress(count_done)

        await self._wait_for_all_movers_done(run_mover_done_ch)

        # Scheduler wind-down: scores predicted-vs-actual makespan
        # (sched.makespan_rel_err) now that the last move has landed.
        self.sched.finish(self._rec.now())

        await self._bump("tot_progress_close")

        self._progress_ch.close()
        self._drained.set()

    async def _run_supply_move(
        self,
        stop_ch: Chan,
        node: str,
        next_moves: list[NextMoves],
        broadcast_stop_ch: Chan,
        broadcast_done_ch: Chan,
    ) -> None:
        """Feed one node one batch, or wait on an in-flight move
        (orchestrate.go:622-696)."""
        next_done_ch = None
        for nm in next_moves:
            if nm.next_done_ch is not None:
                next_done_ch = nm.next_done_ch
                break

        if next_done_ch is None:
            next_done_ch = Chan()
            req = _PartitionMoveReq(
                partition_moves=[
                    PartitionMove(
                        partition=nm.partition,
                        node=nm.moves[nm.next].node,
                        state=nm.moves[nm.next].state,
                        op=nm.moves[nm.next].op,
                    )
                    for nm in next_moves
                ],
                done_ch=next_done_ch,
                t_created=self._rec.now(),
            )

            # A move can target a node with no mover (not in nodes_all).  The
            # reference sends on a nil channel there, which blocks until the
            # stop/broadcast branch fires (orchestrate.go:667 with a missing
            # map key) — the move simply stalls, it does not error.  A fresh
            # never-received Chan reproduces that.  Either way the stall is
            # SURFACED now: a counter bump plus a one-time warning naming
            # the node; with a move deadline set the move fails fast as a
            # MoveFailure instead of silently wedging.
            req_ch = self._map_node_to_req_ch.get(node)
            if req_ch is None:
                self._note_missing_mover(node)
                if self._ft and self.options.move_timeout_s is not None:
                    first = await self._record_batch_failure(
                        node, req.partition_moves, 0, MissingMoverError(node))
                    if self._observers:
                        t_done = self._rec.now()
                        for observer in self._observers:
                            observer.on_batch(node, req.partition_moves,
                                              False, t_done)
                    for nm in next_moves:
                        nm.failed_at = nm.next
                        nm.next = len(nm.moves)
                    await broadcast_done_ch.put(first)
                    return
                req_ch = Chan()
            which, _ = await select(
                (GET, stop_ch),
                (GET, broadcast_stop_ch),
                (PUT, req_ch, req),
            )
            if which == 0:
                await broadcast_done_ch.put(ErrorStopped)
                return
            if which == 1:
                await broadcast_done_ch.put(ErrorInterrupt)
                return
            for nm in next_moves:
                nm.next_done_ch = next_done_ch

        which, value = await select(
            (GET, stop_ch),
            (GET, broadcast_stop_ch),
            (GET, next_done_ch),
        )
        if which == 0:
            await broadcast_done_ch.put(ErrorStopped)
        elif which == 1:
            await broadcast_done_ch.put(ErrorInterrupt)
        else:
            err_val, ok = value
            err = err_val if ok else None
            for nm in next_moves:
                if nm.next_done_ch is next_done_ch:
                    nm.next_done_ch = None
                    if isinstance(err, MoveFailure):
                        # Fault-tolerant abandon: skip this partition's
                        # remaining moves (executing e.g. the "del" after
                        # a failed "add" would corrupt coverage); the
                        # recovery replan re-places it.
                        nm.failed_at = nm.next
                        nm.next = len(nm.moves)
                    else:
                        nm.next += 1
            await broadcast_done_ch.put(err)

    def _note_missing_mover(self, node: str) -> None:
        """Surface the reference's silent moverless-node stall: bump
        ``orchestrate.missing_mover`` every time, warn once per node."""
        self._rec.count("orchestrate.missing_mover")
        if node not in self._missing_mover_warned:
            self._missing_mover_warned.add(node)
            _warnings.warn(
                f"blance_tpu orchestrate: move targets node {node!r} which "
                f"has no mover (not in nodes_all); the move "
                + ("fails fast (move deadline set)"
                   if self._ft and self.options.move_timeout_s is not None
                   else "stalls until stop (reference semantics)"),
                UserWarning, stacklevel=2)

    async def _wait_for_all_movers_done(self, run_mover_done_ch: Chan) -> None:
        """Collect every mover's exit, folding errors into progress
        (orchestrate.go:718-731)."""
        for _ in range(len(self.nodes_all)):
            err, _ok = await run_mover_done_ch.get()

            def count():
                self._bump_sync("tot_run_mover_done")
                if err is not None:
                    self._progress.errors.append(err)
                    self._bump_sync("tot_run_mover_done_err")
                    self._rec.count("orchestrate.errors")
            await self._update_progress(count)


def orchestrate_moves(
    model: PartitionModel,
    options: OrchestratorOptions,
    nodes_all: Optional[list[str]],
    beg_map: PartitionMap,
    end_map: PartitionMap,
    assign_partitions: AssignPartitionsFunc,
    find_move: Optional[FindMoveFunc] = None,
    move_observers: "tuple[MoveObserver, ...]" = (),
) -> Orchestrator:
    """Asynchronously begin reassigning partitions from beg_map to end_map
    (orchestrate.go:240-338).  Must be called with a running asyncio loop;
    the caller must drain ``progress_ch()`` until it closes.

    assign_partitions(stop_ch, node, partitions, states, ops) performs the
    actual data movement for a batch, blocking until done; it may be sync or
    async, and signals failure by raising or returning an Exception.

    find_move(node, moves) -> index picks each node's next move; defaults to
    lowest_weight_partition_move_for_node.

    move_observers: zero or more ``obs.slo.MoveObserver``s, notified
    synchronously after every batch outcome — the live-telemetry hook
    (SLO accounting) that sees each achieved-map delta as it lands.
    """
    if len(beg_map) != len(end_map):
        raise ValueError("mismatched begMap and endMap")
    if assign_partitions is None:
        raise ValueError(
            "callback implementation for AssignPartitionsFunc is expected")

    nodes_all = list(nodes_all or [])
    states = sort_state_names(model)

    # Per-partition flight plans, computed up front without regard to other
    # partitions (orchestrate.go:264-287) — on device when asked.
    map_partition_to_next_moves: dict[str, NextMoves] = {}
    with get_recorder().span(
            "orchestrate.plan_moves", partitions=len(beg_map),
            device_diff=options.device_diff):
        if options.device_diff:
            from ..moves.batch import calc_all_moves

            all_moves = calc_all_moves(
                beg_map, end_map, model, options.favor_min_nodes)
            for partition_name in beg_map:
                map_partition_to_next_moves[partition_name] = NextMoves(
                    partition_name, all_moves[partition_name])
        else:
            for partition_name, beg_partition in beg_map.items():
                end_partition = end_map[partition_name]
                moves = calc_partition_moves(
                    states,
                    beg_partition.nodes_by_state,
                    end_partition.nodes_by_state,
                    options.favor_min_nodes,
                )
                map_partition_to_next_moves[partition_name] = NextMoves(
                    partition_name, moves)

    o = Orchestrator(
        model, options, nodes_all, beg_map, end_map,
        assign_partitions, find_move, map_partition_to_next_moves,
        move_observers=move_observers,
    )
    o._start(o._stop_ch)
    return o
