"""Core data model: partitions, maps, models, hierarchy rules, plan options.

This mirrors the reference's data model (reference: /root/reference/api.go:24-190)
but as Python dataclasses that are trivially JSON round-trippable — the
PartitionMap *is* the checkpoint format of the framework, so keeping it plain
is a design requirement (reference api.go:30,35 json tags).

Unlike the reference, hooks (node scorer / score booster) live on
``PlanOptions`` instead of mutable package globals, so concurrent plans with
different policies can't interfere.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Mapping, Optional

__all__ = [
    "Partition",
    "PartitionMap",
    "PartitionModelState",
    "PartitionModel",
    "HierarchyRule",
    "HierarchyRules",
    "PlanOptions",
    "partition_map_to_json",
    "partition_map_from_json",
    "copy_partition",
    "copy_partition_map",
    "model",
]


@dataclass
class Partition:
    """A distinct shard of a logical resource (reference api.go:28-36).

    ``nodes_by_state`` maps state name -> ordered node list.  Order is
    meaningful: index 0 of the top-priority state is "the primary" used for
    hierarchy anchoring and replica-spread accounting.
    """

    name: str
    nodes_by_state: dict[str, list[str]] = field(default_factory=dict)

    def copy(self) -> "Partition":
        return Partition(
            name=self.name,
            nodes_by_state={s: list(nodes) for s, nodes in self.nodes_by_state.items()},
        )

    def to_json(self) -> dict:
        return {"name": self.name, "nodesByState": self.nodes_by_state}

    @staticmethod
    def from_json(d: Mapping) -> "Partition":
        return Partition(
            name=d["name"],
            nodes_by_state={s: list(nodes) for s, nodes in d.get("nodesByState", {}).items()},
        )


# PartitionMap is keyed by Partition.name (reference api.go:24).
PartitionMap = dict[str, Partition]


@dataclass(frozen=True)
class PartitionModelState:
    """Metadata for one partition state (reference api.go:46-62).

    priority: 0 is highest ("primary" < "replica").
    constraints: how many nodes should hold this state per partition.
    """

    priority: int = 0
    constraints: int = 0


# PartitionModel is keyed by state name (reference api.go:41).
PartitionModel = dict[str, PartitionModelState]


@dataclass(frozen=True)
class HierarchyRule:
    """Rack/zone awareness rule (reference api.go:96-105).

    include_level: ancestors to climb to find the candidate subtree.
    exclude_level: ancestors to climb to find the excluded subtree.
    e.g. include 1 / exclude 0 = "same rack, different node";
    include 2 / exclude 1 = "different rack, same datacenter".
    """

    include_level: int = 0
    exclude_level: int = 0


# HierarchyRules is keyed by state name; value is an ordered rule list, one
# entry consulted per replica ordinal (reference api.go:64-74).
HierarchyRules = dict[str, list[HierarchyRule]]


# Signature of the score-booster hook: (node_weight, stickiness) -> score boost.
# Applied when a node's weight is negative (reference plan.go:675-684,693-697).
NodeScoreBoosterFunc = Callable[[int, float], float]


@dataclass
class PlanOptions:
    """Optional planner knobs (reference api.go:183-190 + package globals).

    The reference exposes ``MaxIterationsPerPlan``, ``CustomNodeSorter`` and
    ``NodeScoreBooster`` as mutable package globals (plan.go:21,580,693); here
    they are per-call options.
    """

    # Override the constraints defined in the model, keyed by state name.
    model_state_constraints: Optional[dict[str, int]] = None
    # Keyed by partition name; default weight 1.
    partition_weights: Optional[dict[str, int]] = None
    # Keyed by state name; default stickiness 1.5.  NOTE (reference quirk,
    # plan.go:104-115): the reference consults state_stickiness only when
    # partition_weights is non-nil; we reproduce that for parity unless
    # ``state_stickiness_standalone`` is set.
    state_stickiness: Optional[dict[str, int]] = None
    # Keyed by node name; default weight 1.  Negative weights trigger the
    # node_score_booster hook.
    node_weights: Optional[dict[str, int]] = None
    # Keyed by node; value is the node's parent in the containment hierarchy.
    node_hierarchy: Optional[dict[str, str]] = None
    # Keyed by state name; replica placement policy.
    hierarchy_rules: Optional[HierarchyRules] = None

    # --- hooks (package globals in the reference) ---
    max_iterations: int = 10  # reference plan.go:21
    node_score_booster: Optional[NodeScoreBoosterFunc] = None  # plan.go:693
    # Custom node scorer: replaces the default score formula entirely.
    # Called as fn(ctx: NodeScoreContext, node: str) -> float; ties still break
    # by node position (reference plan.go:580 CustomNodeSorter).
    node_scorer: Optional[Callable] = None
    # Custom node SORTER: replaces the whole candidate ordering — score
    # AND tie-break policy — like assigning the reference's
    # CustomNodeSorter package var a non-default sort.Interface factory
    # (plan.go:566-580).  Called as fn(ctx: NodeScoreContext,
    # nodes: list[str]) -> list[str]; must return a permutation of
    # ``nodes``.  Takes precedence over node_scorer when both are set.
    node_sorter: Optional[Callable] = None

    # --- compat switches ---
    # When True, state_stickiness applies even without partition_weights
    # (fixes the reference quirk at plan.go:104-115).
    state_stickiness_standalone: bool = False

    # --- backend selection / compilation ---
    # backend="auto" routes to the batched TPU solver when
    # P * N >= this threshold, else the exact native/greedy path.  None =
    # the library default (plan/api.py _AUTO_TPU_THRESHOLD, 256 * 1024 —
    # the crossover point where a device round-trip beats the sequential
    # planner on the calibration hosts).  Deployments with faster
    # interconnects or slower host CPUs should tune this down; tiny
    # embedded runs with no accelerator, up.
    auto_tpu_threshold: Optional[int] = None
    # Opt-in static-shape bucketing for the pure plan_next_map path: pad
    # P and N up to the next size bucket (core/encode.py bucket_size)
    # before the device solve, so repeated calls against a drifting
    # cluster reuse the compiled program instead of recompiling per
    # (P, N).  Pad partitions are weight-0 and pad nodes invalid, so the
    # padded solve's real rows match the unpadded solve's; the padding is
    # stripped before decode.  Off by default: one-shot callers pay the
    # up-to-12.5% padded-FLOPs cost for no reuse benefit.
    shape_bucketing: bool = False
    # Sparse shortlist solver (plan/tensor.solve_sparse): score only a
    # per-partition top-K candidate node list (derived from current
    # placement, hierarchy groups and weights — core/shortlist.py)
    # instead of the dense [P, N] sweep, with fill/price tables kept at
    # full [S, N] width and a per-row dense fallback for exhausted
    # shortlists.  True forces it (requires nesting hierarchy rules:
    # exclude_level < include_level), False forbids it, None = auto —
    # sparse exactly when the dense matrix engine's projected score
    # footprint exceeds the device memory budget.  With a saturating
    # K >= N the sparse result is bit-identical to the dense one.
    sparse: Optional[bool] = None
    # Candidate columns per partition for the sparse solver; None =
    # auto-sized from the constraint structure (core/shortlist.py
    # auto_shortlist_k).  Raise it when plan.sparse.shortlist_exhausted
    # stays nonzero in steady state (docs/DESIGN.md "Sparse solve").
    sparse_k: Optional[int] = None
    # Opt-in fused plan pipeline for the tpu backend: chain
    # encode→solve→move-diff→decode-pack through ONE jitted,
    # buffer-donated device dispatch (plan/tensor.plan_pipeline) instead
    # of the staged encode/solve/decode phases.  The map is bit-identical
    # to the staged path's; the move diff rides along on device (reach it
    # via plan_pipeline or PlannerSession.replan_with_moves to actually
    # consume it).  Off by default: it changes dispatch structure, and
    # one-shot callers with custom hooks fall back anyway.
    fused_pipeline: bool = False

    # --- validation ---
    # Post-solve constraint audit on the batched (tpu) backend: duplicates,
    # placements on removed nodes, unfilled-but-feasible slots surface as
    # UserWarnings (the reference degrades to warnings too, plan.go:231-235).
    # None = auto: on below ~4M P*N cells, off above (the audit is host-side
    # numpy); True/False force it.
    validate_assignment: Optional[bool] = None


def model(**states: tuple[int, int]) -> PartitionModel:
    """Convenience builder: model(primary=(0, 1), replica=(1, 2))."""
    return {
        name: PartitionModelState(priority=pc[0], constraints=pc[1])
        for name, pc in states.items()
    }


def copy_partition(p: Partition) -> Partition:
    return p.copy()


def copy_partition_map(m: PartitionMap) -> PartitionMap:
    """Deep copy (reference plan.go:334-351 toArrayCopy/copyNodesByState)."""
    return {name: p.copy() for name, p in m.items()}


def partition_map_to_json(m: PartitionMap) -> dict:
    return {name: p.to_json() for name, p in m.items()}


def partition_map_from_json(d: Mapping) -> PartitionMap:
    return {name: Partition.from_json(p) for name, p in d.items()}
