"""Containment-hierarchy (rack/zone) tree helpers.

Host-side form of the hierarchy machinery (reference: /root/reference/
plan.go:699-774).  The tree is given as a child->parent map; these helpers
derive parent->children, walk ancestors, and compute include/exclude leaf
sets per HierarchyRule semantics (reference api.go:76-105).

The dense/TPU planner does not use tree recursion: it compresses each level
into per-node group ids so rule checks become integer compares (see
blance_tpu.plan.tensor).
"""

from __future__ import annotations

from collections.abc import Sequence

from .setops import strings_intersect, strings_remove

__all__ = [
    "parents_to_children",
    "find_ancestor",
    "find_leaves",
    "include_exclude_nodes",
    "include_exclude_nodes_intersect",
    "level_group_ids",
]


def parents_to_children(parents: dict[str, str] | None) -> dict[str, list[str]]:
    """Invert child->parent into parent->sorted child list.

    Children are sorted by name for determinism (reference plan.go:703-717).
    """
    rv: dict[str, list[str]] = {}
    if not parents:
        return rv
    for child in sorted(parents):
        rv.setdefault(parents[child], []).append(child)
    return rv


def find_ancestor(node: str, parents: dict[str, str] | None, level: int) -> str:
    """Walk up ``level`` parents; a missing parent yields "" (plan.go:755-762)."""
    parents = parents or {}
    for _ in range(level):
        node = parents.get(node, "")
    return node


def find_leaves(node: str, children: dict[str, list[str]]) -> list[str]:
    """All leaf descendants; a childless node is itself a leaf (plan.go:764-774)."""
    kids = children.get(node)
    if not kids:
        return [node]
    rv: list[str] = []
    for c in kids:
        rv.extend(find_leaves(c, children))
    return rv


def include_exclude_nodes(
    node: str,
    include_level: int,
    exclude_level: int,
    parents: dict[str, str] | None,
    children: dict[str, list[str]],
) -> list[str]:
    """leaves(ancestor(include_level)) minus leaves(ancestor(exclude_level)).

    Reference plan.go:723-734; rule semantics documented at api.go:76-105.
    """
    inc = find_leaves(find_ancestor(node, parents, include_level), children)
    exc = find_leaves(find_ancestor(node, parents, exclude_level), children)
    return strings_remove(inc, exc)


def include_exclude_nodes_intersect(
    nodes: Sequence[str],
    include_level: int,
    exclude_level: int,
    parents: dict[str, str] | None,
    children: dict[str, list[str]],
) -> list[str]:
    """Intersection of include_exclude_nodes over all anchors (plan.go:738-753).

    The anchors are the primary plus all hierarchy picks made so far, so later
    picks are cognizant of earlier ones.
    """
    rv: list[str] = []
    first = True
    for node in nodes:
        res = include_exclude_nodes(node, include_level, exclude_level, parents, children)
        if first:
            rv = res
            first = False
            continue
        rv = strings_intersect(rv, res)
    return rv


def level_group_ids(
    nodes: Sequence[str], parents: dict[str, str] | None, max_level: int
) -> list[list[int]]:
    """Compress the tree into per-level group ids for the dense planner.

    Returns ``gid[level][i]`` = integer id of node ``nodes[i]``'s level-th
    ancestor (level 0 = the node itself).  Two nodes share a level-L subtree
    iff their level-L group ids are equal — which turns HierarchyRule
    include/exclude checks into integer comparisons with no N×N masks
    (SURVEY.md §7 hard part 2).  A missing ancestor maps every orphan to the
    shared "" group, matching find_ancestor's "" convention.
    """
    out: list[list[int]] = []
    get = (parents or {}).get
    names: list[str] = list(nodes)
    for level in range(max_level + 1):
        if level:
            # One parent step per level — identical to find_ancestor's
            # from-scratch walk (same get() sequence) at O(L*N) total
            # instead of O(L^2*N), which matters at 10k nodes.
            names = [get(nm, "") for nm in names]
        interned: dict[str, int] = {}
        row: list[int] = []
        for nm in names:
            if nm not in interned:
                interned[nm] = len(interned)
            row.append(interned[nm])
        out.append(row)
    return out
