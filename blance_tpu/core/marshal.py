"""Loader for the native marshalling extension (native/marshal.c).

Compiles the CPython extension on first use with the session's own
interpreter headers (no pip, no setuptools build step — same pattern as the
native planner, plan/native.py) and imports it as a real module.  All users
go through :func:`get` and fall back to pure Python when the toolchain or
headers are unavailable, so the framework never hard-depends on a compiler.
"""

from __future__ import annotations

import importlib.util
import os
import sysconfig

from ..utils.nativebuild import compile_cached

__all__ = ["get", "available"]

_MOD = None
_FAILED = False


def _build_dir() -> str:
    return os.path.join(os.path.dirname(__file__), "_native_build")


def _source_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
        "native", "marshal.c")


def get() -> object:
    """The extension module, or None when unavailable."""
    global _MOD, _FAILED
    if _MOD is not None or _FAILED:
        return _MOD
    src = _source_path()
    ext = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so = os.path.join(_build_dir(), "_blance_marshal" + ext)
    include = sysconfig.get_paths()["include"]
    if not compile_cached(src, so, ["gcc", "-O2", "-shared", "-fPIC",
                                    f"-I{include}", "-o", so, src]):
        _FAILED = True
        return None
    try:
        spec = importlib.util.spec_from_file_location("_blance_marshal", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except (OSError, ImportError):
        _FAILED = True
        return None
    _MOD = mod
    return _MOD


def available() -> bool:
    return get() is not None
