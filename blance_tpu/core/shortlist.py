"""Per-partition top-K candidate shortlists for the sparse solver.

The dense auction scores every partition against every node — an
f32 [P, N] sweep whose memory wall blocks the next order of magnitude
(ROADMAP item 2: 1M x 10k is a ~40 GB score tensor).  But the candidate
set compresses dramatically (arxiv 2510.12196): stickiness makes most
partitions' viable rows near-diagonal (their previous nodes), hierarchy
rules confine replicas to a handful of groups, and balance pressure only
ever pulls load toward the emptiest nodes.  Following TOAST
(arxiv 2508.15010), the shortlist is derived STATICALLY from the
constraint structure before the sweep, not re-discovered per round:

1. **Sticky candidates** — every node the partition currently holds
   (prev[P, S, R]): the warm-carry steady state re-pins these, so they
   must always be in reach.
2. **Rule-group representatives** — per hierarchy rule (include,
   exclude) with exclude strictly finer than include (the nesting tree
   shape the solver's sparse path requires): the least-loaded valid
   node of each exclude-group ("rack") is that group's representative;
   each partition gets the ``reps`` least-loaded representatives inside
   its previous primary's include-group ("zone"), so a rule-satisfying
   target exists for every replica ordinal without scanning N columns.
3. **Global attractors + coverage** — a few globally least-loaded valid
   nodes by weight-normalized seed fill, shared by every row (fresh or
   empty nodes must attract load from every partition), plus a per-row
   rotated window over the valid-node ranking so unanchored rows (a
   fresh cluster) collectively cover all N nodes instead of herding
   onto one shared top-K.

Priority is exactly that order: when the union exceeds K, attractors are
dropped first and sticky candidates never.  Rows are deduplicated and
returned sorted ascending with -1 padding at the tail — ascending order
is what makes a saturating K = N shortlist the identity permutation, so
the sparse solve's tie-breaks match the dense engine's lowest-node-id
rule bit-for-bit.

The builder is a pure jittable array program (`build_shortlist_core`)
so the fused sparse plan pipeline can run it INSIDE its single device
dispatch; `build_shortlist` is the host-facing jitted spelling.

A shortlist is a HINT, not a correctness surface: the sparse solver
detects rows whose shortlist cannot reach the globally attainable rule
tier (or has no feasible candidate at all) and routes them through the
per-row dense fallback, so audit contracts hold for ANY shortlist — the
builder only controls how rarely that escape hatch fires.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

__all__ = ["auto_shortlist_k", "build_shortlist", "build_shortlist_core",
           "shortlist_rules_nest"]


def shortlist_rules_nest(rules: tuple) -> bool:
    """True when every rule's exclude level is strictly finer than its
    include level — the tree shape the sparse solver's group-counting
    tier floor (and rule step 2 above) requires."""
    return all(exc < inc for state_rules in rules
               for (inc, exc) in state_rules)


def auto_shortlist_k(n: int, constraints: tuple, rules: tuple) -> int:
    """Default K for an (N, constraints, rules) problem.

    Sized to cover the sticky set (every held slot), a rule
    representative per constrained ordinal of every rule-bearing state,
    and a margin of global attractors — then rounded up to a lane-
    friendly multiple of 8 and clamped to N.  Guidance (docs/DESIGN.md
    "Sparse solve"): raise K when exhaustion counters
    (``plan.sparse.shortlist_exhausted``) are nonzero in steady state;
    lower it toward this floor when they stay at zero.
    """
    slots = sum(max(int(c), 0) for c in constraints)
    ruled = sum(max(int(c), 0) for c, state_rules in zip(constraints, rules)
                if state_rules)
    k = 2 * slots + 2 * ruled + 8
    k = max(16, k)
    k = -(-k // 8) * 8
    return min(max(n, 1), k)


def _seed_load(prev, pweights, nweights, n: int):
    """[N] weight-normalized seed fill from the previous placement — the
    same quantity the solver's balance term divides, so 'least loaded'
    here agrees with where the auction will push load."""
    import jax.numpy as jnp

    ids = prev.reshape(prev.shape[0], -1)
    w = jnp.broadcast_to(pweights[:, None], ids.shape).reshape(-1)
    flat = jnp.where(ids >= 0, ids, n).reshape(-1)
    fill = jnp.zeros(n, jnp.float32).at[flat].add(w, mode="drop")
    w_div = jnp.where(nweights > 0, nweights, 1.0)
    return fill / w_div


def _group_reps(load_rank, gids_lv, gid_valid_lv, valid, n: int):
    """[N] exclude-group -> representative node id (-1 = empty group):
    the valid node with the best (lowest) load rank in each group.
    Group ids are dense per level (< N), so the table is [N]-shaped."""
    import jax.numpy as jnp

    ok = valid & gid_valid_lv & (gids_lv >= 0)
    g = jnp.where(ok, gids_lv, n)
    rank = jnp.where(ok, load_rank, n)
    best = jnp.full(n, n, jnp.int32).at[g].min(
        rank.astype(jnp.int32), mode="drop")
    # Invert: node whose rank equals its group's best wins (ranks are a
    # permutation, so the hit is unique).
    node_of_rank = jnp.full(n + 1, -1, jnp.int32).at[
        jnp.clip(rank.astype(jnp.int32), 0, n)].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop")
    return jnp.where(best < n, node_of_rank[jnp.clip(best, 0, n)], -1)


def _rep_table(rep, load_rank, gids_inc, gid_valid_inc, m: int, n: int):
    """[N, m] include-group -> its ``m`` best exclude-group
    representatives (by load rank, -1 padded).

    Built by sorting exclude groups by (include parent of their rep,
    rep's load rank) and scattering the first m of each segment.
    """
    import jax.lax as lax
    import jax.numpy as jnp

    has = rep >= 0
    rep_c = jnp.clip(rep, 0, n - 1)
    parent = jnp.where(has & gid_valid_inc[rep_c], gids_inc[rep_c], n)
    rank = jnp.where(has, load_rank[rep_c], n).astype(jnp.int32)
    # Sort exclude groups by rep rank, then stable-group by parent:
    # within a parent, reps come out least-loaded first.
    perm1 = jnp.argsort(rank, stable=True)
    perm = perm1[jnp.argsort(parent[perm1], stable=True)]
    parent_s = parent[perm]
    rep_s = rep[perm]
    seg_start = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), parent_s[1:] != parent_s[:-1]])
    pos_all = jnp.arange(n, dtype=jnp.int32)
    seg_base = lax.cummax(jnp.where(seg_start, pos_all, -1))
    segpos = pos_all - seg_base
    ok = (parent_s < n) & (rep_s >= 0) & (segpos < m)
    flat_idx = jnp.where(ok, parent_s * m + segpos, n * m)
    return jnp.full(n * m, -1, jnp.int32).at[flat_idx].set(
        rep_s, mode="drop").reshape(n, m)


def _dedup_truncate_sort(cand, k: int, n: int):
    """[P, C] priority-ordered candidate ids -> [P, k] deduplicated,
    ascending, -1-padded shortlist.  Keep-first dedup: earlier columns
    (higher priority) survive, so sticky candidates never drop."""
    import jax.numpy as jnp

    c_width = cand.shape[1]
    ids = jnp.where(cand >= 0, cand, n)  # absent -> sentinel n
    # Stable id sort keeps original column order (= priority) inside
    # duplicate runs, so the first kept copy is the highest-priority one.
    ord1 = jnp.argsort(ids, axis=1, stable=True)
    ids_s = jnp.take_along_axis(ids, ord1, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((ids.shape[0], 1), jnp.bool_),
         (ids_s[:, 1:] == ids_s[:, :-1]) & (ids_s[:, 1:] < n)], axis=1)
    # Rank survivors by priority; dups/sentinels sink past every real
    # column and are truncated with the overflow.
    key = jnp.where(dup | (ids_s >= n), c_width, ord1)
    ord2 = jnp.argsort(key, axis=1, stable=True)
    kept = jnp.take_along_axis(ids_s, ord2, axis=1)[:, :k]
    kept_key = jnp.take_along_axis(key, ord2, axis=1)[:, :k]
    kept = jnp.where(kept_key >= c_width, n, kept)
    out = jnp.sort(kept, axis=1)  # ascending; sentinels sink to the tail
    return jnp.where(out >= n, -1, out).astype(jnp.int32)


def build_shortlist_core(prev, pweights, nweights, valid, gids, gid_valid,
                         constraints: tuple, rules: tuple, k: int,
                         reps: Optional[int] = None):
    """Traceable builder core: [P, S, R] placement -> [P, K'] shortlist
    (K' = min(k, N)); see the module docstring for the derivation.

    ``k``/``reps`` are static.  Saturating K >= N returns the identity
    permutation broadcast to every row — the spelling that makes the
    sparse solve bit-identical to the dense one.
    """
    import jax.numpy as jnp

    p = prev.shape[0]
    n = nweights.shape[0]
    if n == 0 or p == 0:
        return jnp.zeros((p, 0), jnp.int32)
    if k >= n:
        return jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (p, n))
    k = max(int(k), 1)

    load = _seed_load(prev, pweights, nweights, n)
    # Global least-loaded ranking; ties break by node id (stable sort).
    order = jnp.argsort(jnp.where(valid, load, jnp.inf),
                        stable=True).astype(jnp.int32)
    load_rank = jnp.zeros(n, jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))

    cols = [prev.reshape(p, -1)]  # sticky candidates, highest priority

    if reps is None:
        reps = max([1] + [int(c) + 1 for c, state_rules
                          in zip(constraints, rules) if state_rules])
        reps = min(reps, max(1, k // 2))
    anchor = prev[:, 0, 0]
    anchor_c = jnp.clip(anchor, 0, n - 1)
    seen: set = set()
    for state_rules in rules:
        for (inc, exc) in state_rules:
            if (inc, exc) in seen or not (exc < inc):
                continue
            seen.add((inc, exc))
            rep = _group_reps(load_rank, gids[exc], gid_valid[exc],
                              valid, n)
            table = _rep_table(rep, load_rank, gids[inc], gid_valid[inc],
                               reps, n)
            g = jnp.where((anchor >= 0) & gid_valid[inc][anchor_c],
                          gids[inc][anchor_c], -1)
            row_reps = jnp.where(
                (g[:, None] >= 0),
                table[jnp.clip(g, 0, n - 1)], -1)
            cols.append(row_reps)

    n_fixed = sum(c.shape[1] for c in cols)
    k_glob = max(k - min(n_fixed, k - 1), 1)
    # Global attractors split two ways.  A few TRUE least-loaded nodes,
    # shared by every row: a fresh/empty node must attract load from
    # everyone.  The rest is a per-row ROTATED window over the valid-node
    # ranking (Weyl-hash offset): identical windows would herd every
    # unanchored row (a fresh cluster: no sticky nodes, no rule anchors)
    # onto the same K nodes and leave the force step to cram them past
    # the capacity rail — coverage, not just greed, is what lets the
    # auction's price/rail spread fresh load across all N nodes.
    g_top = min(4, k_glob)
    cols.append(jnp.broadcast_to(order[:g_top], (p, g_top)))
    k_cov = k_glob - g_top
    if k_cov > 0:
        n_valid = jnp.maximum(
            jnp.sum(valid.astype(jnp.int32)), jnp.int32(1))
        rowpos = (jnp.arange(p, dtype=jnp.int32) * jnp.int32(40503)) \
            % n_valid
        offs = rowpos[:, None] + jnp.arange(k_cov, dtype=jnp.int32)[None, :]
        cols.append(order[offs % n_valid])

    cand = jnp.concatenate(cols, axis=1)
    return _dedup_truncate_sort(cand, k, n)


_STATICS = ("constraints", "rules", "k", "reps")
_build_jit = None


def build_shortlist(prev, pweights, nweights, valid, gids, gid_valid,
                    constraints: tuple, rules: tuple, k: int,
                    reps: Optional[int] = None):
    """Host-facing jitted spelling of :func:`build_shortlist_core`."""
    global _build_jit
    import jax
    import jax.numpy as jnp

    if _build_jit is None:
        _build_jit = partial(jax.jit, static_argnames=_STATICS)(
            build_shortlist_core)
    return _build_jit(
        jnp.asarray(prev), jnp.asarray(pweights), jnp.asarray(nweights),
        jnp.asarray(valid), jnp.asarray(gids), jnp.asarray(gid_valid),
        constraints=tuple(constraints),
        rules=tuple(tuple(r) for r in rules), k=int(k),
        reps=None if reps is None else int(reps))
