"""Ordered string-set operations.

These are the data-model-level primitives the planner and move calculus are
built from (reference: /root/reference/misc.go:13-66).  All operations are
order-preserving with respect to their first argument, which is load-bearing:
node ordering encodes priority (replica ordinals) throughout the framework.

On the dense/TPU path these same operations are boolean-mask ops over int32
node-id arrays (see blance_tpu.plan.tensor); this module is the host-side,
small-problem form.
"""

from __future__ import annotations

from collections.abc import Iterable, Sequence

__all__ = [
    "strings_to_set",
    "strings_remove",
    "strings_intersect",
    "strings_dedup",
]


def strings_to_set(strs: Iterable[str] | None) -> set[str] | None:
    """Build a membership set; None passes through (reference misc.go:13-22)."""
    if strs is None:
        return None
    return set(strs)


def strings_remove(strs: Sequence[str], remove: Sequence[str] | None) -> list[str]:
    """strs minus remove, preserving strs order (reference misc.go:27-36)."""
    if not remove:
        return list(strs)
    removed = set(remove)
    return [s for s in strs if s not in removed]


def strings_intersect(a: Sequence[str], b: Sequence[str] | None) -> list[str]:
    """Intersection in a's order, deduplicated (reference misc.go:40-51)."""
    if not b:
        return []
    bset = set(b)
    seen: set[str] = set()
    rv: list[str] = []
    for s in a:
        if s in bset and s not in seen:
            seen.add(s)
            rv.append(s)
    return rv


def strings_dedup(a: Sequence[str]) -> list[str]:
    """Deduplicate, preserving first-occurrence order (reference misc.go:55-66)."""
    seen: set[str] = set()
    rv: list[str] = []
    for s in a:
        if s not in seen:
            seen.add(s)
            rv.append(s)
    return rv
