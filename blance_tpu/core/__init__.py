"""blance_tpu.core subpackage."""
