"""Dense encoding: PartitionMap <-> int32/float32 arrays.

The reference's data model is maps of strings (reference api.go:24-36); the
TPU planner needs dense tensors.  This module interns node/partition/state
names to ids and packs the planning problem into arrays:

- assign[P, S, R] : int32 node ids, -1 = empty slot (R = max slots seen).
- constraints[S]  : per-state target copy counts, priority-ordered.
- weights         : float32 partition/node weights.
- hierarchy       : per-level group ids per node (see
  core.hierarchy.level_group_ids) so include/exclude rules are integer
  compares, never N x N masks (SURVEY.md §7 hard part 2).

Partitions are ordered by the same zero-padded-numeric-else-raw name key the
planner sorts by, so dense ids match the greedy planner's deterministic
iteration order.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from . import marshal as _marshal
from .hierarchy import find_ancestor, level_group_ids
from .setops import strings_remove
from .types import (
    Partition,
    PartitionMap,
    PartitionModel,
    PlanOptions,
)

__all__ = ["DenseProblem", "encode_problem", "decode_assignment",
           "bucket_size", "pad_to", "pad_problem_arrays",
           "stack_problem_arrays", "pack_assignment_core",
           "pack_assignment", "prev_from_entries_core",
           "prev_from_entries", "pack_slot_rows", "strip_prev_rows"]

# Host-side array annotation shorthand.  numpy's ndarray is generic
# under the stubs, and every module under the mypy
# disallow_any_generics ratchet must parameterize it at each spelling.
# Dtype precision is not what that gate buys (the dense encoding is
# int32/float32 by construction, asserted here at encode time) —
# structural parameterization is.
NPArray = np.ndarray[Any, np.dtype[Any]]

# Shape-bucket granularity: buckets per power-of-two octave.  8 keeps the
# worst-case padding overhead at 1/8 = 12.5% of the axis while collapsing
# the jit-cache key space to ~8 entries per octave — the GSPMD insight
# (arXiv:2105.04663) that repeated invocation is cheap exactly when the
# compiled program's static shapes are reused.
_BUCKET_GRANULARITY = 8


def bucket_size(x: int, granularity: int = _BUCKET_GRANULARITY) -> int:
    """Round ``x`` up to the next static-shape bucket.

    Buckets are multiples of 2**floor(log2(x)) / granularity, i.e. the
    octave [2^k, 2^(k+1)) is split into ``granularity`` evenly spaced
    sizes.  A cluster drifting 1000 -> 1007 -> 998 nodes maps to one
    bucket (1024), so every replan hits the jit cache instead of
    recompiling; the pad rows/columns are inert by construction (weight-0
    partitions, invalid nodes — the same trick parallel/sharded.py uses
    for mesh divisibility)."""
    if x <= granularity:
        return max(x, 0)
    step = max(1, (1 << (x.bit_length() - 1)) // granularity)
    return -(-x // step) * step


def pad_to(arr: np.ndarray, axis: int, target: int,
           fill: float | int | bool) -> np.ndarray:
    """Pad ``arr`` along ``axis`` up to ``target`` entries with ``fill``;
    no-op when already that long.  The one padding spelling shared by
    shape bucketing here and mesh-divisibility padding in
    parallel/sharded.py."""
    cur = arr.shape[axis]
    if cur >= target:
        return arr
    pad_shape = list(arr.shape)
    pad_shape[axis] = target - cur
    return np.concatenate(
        [arr, np.full(pad_shape, fill, arr.dtype)], axis=axis)


def pad_problem_arrays(
    prev: np.ndarray,
    partition_weights: np.ndarray,
    node_weights: np.ndarray,
    valid_node: np.ndarray,
    stickiness: np.ndarray,
    gids: np.ndarray,
    gid_valid: np.ndarray,
    p_target: int,
    n_target: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray,
           np.ndarray, np.ndarray]:
    """Pad one problem's solver arrays to (p_target, n_target), inertly.

    THE bit-neutral padding recipe, shared by plan_next_map_tpu's
    shape-bucketed path and the fleet batch stacker (plan/fleet.py):
    pad partitions are weight-0 bidders (their assignments are sliced
    off by the caller) and pad nodes invalid (valid=False => zero
    capacity, +INF score, gid_valid=False), the same inert-padding
    contract parallel/sharded.py relies on, so the real rows solve
    identically to the unpadded problem.  Parameters and the returned
    tuple both follow the solver's positional order (prev, pweights,
    nweights, valid, stickiness, gids, gid_valid) so the call sites
    splat straight into solve_dense and friends."""
    prev = pad_to(prev, 0, p_target, -1)
    partition_weights = pad_to(partition_weights, 0, p_target, 0.0)
    stickiness = pad_to(stickiness, 0, p_target, 0.0)
    node_weights = pad_to(node_weights, 0, n_target, 1.0)
    valid_node = pad_to(valid_node, 0, n_target, False)
    gids = pad_to(gids, 1, n_target, -1)
    gid_valid = pad_to(gid_valid, 1, n_target, False)
    return (prev, partition_weights, node_weights, valid_node,
            stickiness, gids, gid_valid)


def stack_problem_arrays(
    padded: "list[tuple[np.ndarray, ...]]",
) -> tuple[np.ndarray, ...]:
    """Stack B same-shape padded array tuples into [B, ...] batch
    tensors (one np.stack per operand, solver positional order
    preserved).  The batch analog of pad_problem_arrays: pad first so
    every element of a bucket class shares its static shape, then
    stack — the [B, P, S, N] problem tensor the fleet solver vmaps
    over."""
    if not padded:
        raise ValueError("stack_problem_arrays: empty batch")
    width = len(padded[0])
    return tuple(
        np.stack([np.asarray(arrs[i]) for arrs in padded])
        for i in range(width))


# --- device integer cores ---------------------------------------------------
#
# The string<->id interning at the map edges is inherently host work, but
# the INTEGER cores of encode (filling prev[P, S, R] from interned
# entries) and decode (packing each state row's non-empty slots left and
# counting them) are pure array programs.  They live here as traceable
# jnp functions so the fused plan pipeline (plan/tensor.plan_pipeline)
# can run them INSIDE its single jitted dispatch — decode's host share
# shrinks to one id->name gather, and nothing round-trips between solve
# and diff.  jax imports stay function-local: this module is also the
# greedy/native path's encoder, which must import without touching jax.


def pack_assignment_core(assign):  # type: ignore[no-untyped-def]
    """Decode's integer core, traceable: pack every (partition, state)
    row's non-empty slots left (stable, preserving slot order) and count
    them.  [P, S, R] int32 -> (packed [P, S, R] int32, counts [P, S]
    int32).  Bit-equivalent to the numpy pack in decode_assignment
    (pinned by tests), so device-packed rows feed the same host
    materializer."""
    import jax.numpy as jnp

    mask = assign >= 0
    order = jnp.argsort(~mask, axis=2, stable=True)
    packed = jnp.take_along_axis(assign, order, axis=2)
    counts = jnp.sum(mask, axis=2, dtype=jnp.int32)
    return packed, counts


_pack_assignment_jit = None


def pack_assignment(assign):  # type: ignore[no-untyped-def]
    """Host-facing jitted spelling of :func:`pack_assignment_core`."""
    global _pack_assignment_jit
    if _pack_assignment_jit is None:
        import jax

        _pack_assignment_jit = jax.jit(pack_assignment_core)
    return _pack_assignment_jit(assign)


def prev_from_entries_core(pi, si, ri, node, p: int, s: int, r: int):  # type: ignore[no-untyped-def]
    """Encode's integer core, traceable: scatter interned (partition,
    state, slot, node) entry columns into a dense prev[P, S, R] (-1
    empties).  Out-of-range entries drop (mode="drop"), so callers can
    pad entry lists with -1 rows.  Equivalent to encode_problem's host
    fill loop for already-interned entries (pinned by tests) — the
    spelling a device-resident caller uses to apply map deltas without
    re-marshalling strings."""
    import jax.numpy as jnp

    flat = pi * (s * r) + si * r + ri
    flat = jnp.where((pi >= 0) & (si >= 0) & (ri >= 0), flat, p * s * r)
    return jnp.full((p * s * r,), -1, jnp.int32).at[flat].set(
        node.astype(jnp.int32), mode="drop").reshape(p, s, r)


_prev_from_entries_jit = None


def prev_from_entries(pi, si, ri, node, p: int, s: int, r: int):  # type: ignore[no-untyped-def]
    """Jitted spelling of :func:`prev_from_entries_core` (static dims)."""
    global _prev_from_entries_jit
    if _prev_from_entries_jit is None:
        import jax
        from functools import partial as _partial

        _prev_from_entries_jit = _partial(
            jax.jit, static_argnames=("p", "s", "r"))(prev_from_entries_core)
    return _prev_from_entries_jit(pi, si, ri, node, p=p, s=s, r=r)


def pack_slot_rows(rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host pack of ``[..., S, R]`` assignment rows: non-empty slots
    left (stable, preserving slot order) + per-(row, state) counts.

    THE numpy spelling of decode_assignment's per-state pack (argsort
    on the empty mask, ``kind="stable"``) lifted to whole rows, and the
    host twin of the traceable :func:`pack_assignment_core` — shared by
    the encode-residency layer (plan/resident.py) so its delta-patched
    ``prev`` is bit-equal to what a fresh ``encode_problem`` of the
    decoded map would scatter."""
    mask = rows >= 0
    order = np.argsort(~mask, axis=-1, kind="stable")
    packed = np.take_along_axis(rows, order, axis=-1)
    counts = mask.sum(axis=-1).astype(np.int64)
    return packed, counts


def strip_prev_rows(prev: np.ndarray,
                    node_ids: np.ndarray) -> tuple[np.ndarray,
                                                   np.ndarray]:
    """Remove every placement on ``node_ids`` from ``prev`` [P, S, R]
    and re-pack the touched rows left; returns ``(patched prev — a new
    array, dirty row mask [P])``.

    The array twin of ``rebalance._strip_nodes`` + re-encode: a fresh
    ``encode_problem`` of the stripped map fills each touched row with
    the surviving entries in their original order, packed left — which
    is exactly mask-to-(-1) + :func:`pack_slot_rows` on those rows.
    Untouched rows are returned byte-identical (same values, new array
    object: callers memoize on array identity, so an in-place patch
    could serve stale memo hits)."""
    hit = np.isin(prev, node_ids)
    dirty = hit.any(axis=(1, 2))
    out = prev.copy()
    if dirty.any():
        sub = out[dirty]
        sub[hit[dirty]] = -1
        packed, _counts = pack_slot_rows(sub)
        out[dirty] = packed
    return out, dirty


@dataclass
class DenseProblem:
    """A fully interned planning problem, ready for the tensor planner."""

    nodes: list[str]  # id -> name, in nodes_all order (ties break by this)
    partitions: list[str]  # id -> name, in planner sort order
    states: list[str]  # priority-ordered (sort_state_names)

    constraints: np.ndarray  # [S] int32
    prev: np.ndarray  # [P, S, R] int32 node ids, -1 empty
    partition_weights: np.ndarray  # [P] float32
    node_weights: np.ndarray  # [N] float32 (raw; may be negative)
    valid_node: np.ndarray  # [N] bool — False for nodes_to_remove
    stickiness: np.ndarray  # [P, S] float32

    # Hierarchy: group ids per level per node; level 0 = the node itself.
    # gids[l, n] == gids[l, m] iff nodes n, m share their level-l ancestor.
    gids: np.ndarray  # [L, N] int32
    gid_valid: np.ndarray  # [L, N] bool — ancestor exists at that level
    # Per state, list of (include_level, exclude_level) rules.
    rules: dict[int, list[tuple[int, int]]]

    @property
    def P(self) -> int:
        return len(self.partitions)

    @property
    def N(self) -> int:
        return len(self.nodes)

    @property
    def S(self) -> int:
        return len(self.states)

    @property
    def R(self) -> int:
        return self.prev.shape[2] if self.prev.size else 0


def encode_problem(
    prev_map: PartitionMap,
    partitions_to_assign: PartitionMap,
    nodes_all: list[str],
    nodes_to_remove: Optional[list[str]],
    model: PartitionModel,
    opts: PlanOptions,
) -> DenseProblem:
    """Intern and pack a planning problem into dense arrays."""
    # Deferred to avoid a core <-> plan import cycle at package init; the
    # greedy key function is the single source of truth so dense ids match
    # the greedy planner's deterministic iteration order exactly.
    from ..plan.greedy import sort_state_names, sorted_by_partition_name

    nodes = list(nodes_all)
    node_index = {n: i for i, n in enumerate(nodes)}

    partitions = sorted_by_partition_name(partitions_to_assign.keys())
    states = sort_state_names(model)
    state_index = {s: i for i, s in enumerate(states)}

    constraints = np.zeros(len(states), dtype=np.int32)
    for s, st in model.items():
        c = st.constraints
        if opts.model_state_constraints is not None:
            c = opts.model_state_constraints.get(s, c)
        constraints[state_index[s]] = c

    # Slot depth: enough for the widest constraint and the widest prev row.
    # The R scan and the [P, S, R] fill each touch every cell once; at 100k
    # partitions that dict/list traversal dominates end-to-end wall-clock,
    # so both run in the native marshalling layer when it's available
    # (native/marshal.c), with this pure-Python path as the fallback.
    # The C fast path is stricter about shapes (real dicts, real lists);
    # any structural surprise raises TypeError there and we fall back to
    # this loop, which tolerates arbitrary Mappings/Sequences.
    native = _marshal.get()
    r_max = int(constraints.max()) if len(constraints) else 0
    filled = None
    if native is not None:
        try:
            r_max = max(r_max, native.max_slots(
                partitions, prev_map, partitions_to_assign, state_index))
            r_max = max(r_max, 1)
            P, S = len(partitions), len(states)
            filled = np.empty((P, S, r_max), dtype=np.int32)
            native.fill_prev(filled, P, S, r_max, partitions, prev_map,
                             partitions_to_assign, state_index, node_index)
        except (TypeError, AttributeError):
            # AttributeError: a None/falsy entry in prev_map reaches
            # .nodes_by_state in C; the Python loop below tolerates it
            # via the `or partitions_to_assign[...]` fallthrough.
            filled = None
            r_max = int(constraints.max()) if len(constraints) else 0
    if filled is None:
        for pname in partitions:
            src = prev_map.get(pname) or partitions_to_assign[pname]
            for s, ns in src.nodes_by_state.items():
                if s in state_index:
                    r_max = max(r_max, len(ns))
        r_max = max(r_max, 1)

    P, S, N = len(partitions), len(states), len(nodes)
    if filled is not None:
        prev = filled
    else:
        prev = np.full((P, S, r_max), -1, dtype=np.int32)
        for pi, pname in enumerate(partitions):
            src = prev_map.get(pname) or partitions_to_assign.get(pname)
            if src is None:
                continue
            for s, ns in src.nodes_by_state.items():
                si = state_index.get(s)
                if si is None:
                    continue
                for ri, node in enumerate(ns[:r_max]):
                    prev[pi, si, ri] = node_index.get(node, -1)

    pweights = np.ones(P, dtype=np.float32)
    if opts.partition_weights:
        for pi, pname in enumerate(partitions):
            pweights[pi] = opts.partition_weights.get(pname, 1)

    nweights = np.ones(N, dtype=np.float32)
    if opts.node_weights:
        for ni, n in enumerate(nodes):
            nweights[ni] = opts.node_weights.get(n, 1)

    valid = np.ones(N, dtype=bool)
    if nodes_to_remove:
        removed = set(nodes_to_remove)
        for ni, n in enumerate(nodes):
            if n in removed:
                valid[ni] = False

    # Stickiness per (partition, state), with the reference's resolution
    # order (plan.go:104-115): partition weight if present, else state
    # stickiness (gated on partition_weights presence unless the standalone
    # compat switch), else 1.5.
    stickiness = np.full((P, S), 1.5, dtype=np.float32)
    pw = opts.partition_weights
    ss = opts.state_stickiness
    ss_active = ss is not None and (pw is not None or opts.state_stickiness_standalone)
    if pw or ss_active:
        for pi, pname in enumerate(partitions):
            if pw is not None and pname in pw:
                stickiness[pi, :] = pw[pname]
            elif ss_active:
                for si, s in enumerate(states):
                    if s in ss:
                        stickiness[pi, si] = ss[s]

    # Hierarchy group ids.  Levels needed = max level referenced by any rule.
    rules_by_state: dict[int, list[tuple[int, int]]] = {}
    max_level = 0
    if opts.hierarchy_rules:
        for s, rl in opts.hierarchy_rules.items():
            si = state_index.get(s)
            if si is None:
                continue
            rules_by_state[si] = [
                (r.include_level, r.exclude_level) for r in rl
            ]
            for r in rl:
                max_level = max(max_level, r.include_level, r.exclude_level)

    gid_rows = level_group_ids(nodes, opts.node_hierarchy, max_level)
    gids = np.asarray(gid_rows, dtype=np.int32).reshape(max_level + 1, N) \
        if N else np.zeros((max_level + 1, 0), np.int32)
    gid_valid = np.ones((max_level + 1, N), dtype=bool)
    for level in range(max_level + 1):
        for ni, n in enumerate(nodes):
            gid_valid[level, ni] = find_ancestor(n, opts.node_hierarchy, level) != ""

    return DenseProblem(
        nodes=nodes,
        partitions=partitions,
        states=states,
        constraints=constraints,
        prev=prev,
        partition_weights=pweights,
        node_weights=nweights,
        valid_node=valid,
        stickiness=stickiness,
        gids=gids,
        gid_valid=gid_valid,
        rules=rules_by_state,
    )


def decode_assignment(
    problem: DenseProblem,
    assign: np.ndarray,  # [P, S, R] int32 node ids, -1 empty
    partitions_to_assign: PartitionMap,
    nodes_to_remove: Optional[list[str]] = None,
    *,
    packed: Optional[np.ndarray] = None,  # [P, S, R] device-packed rows
    counts: Optional[np.ndarray] = None,  # [P, S] per-row filled counts
) -> tuple[PartitionMap, dict[str, list[str]]]:
    """Dense assignment -> PartitionMap + constraint-shortfall warnings.

    States absent from the model keep their (removed-node-stripped) previous
    assignment, matching the greedy planner's pass-through of unmodeled
    states.  Vectorized over P: the id->name gather, empty-slot packing and
    shortfall detection run as whole-array numpy ops so decode stays off the
    end-to-end critical path at 100k partitions (BASELINE.md).

    ``packed``/``counts`` (both or neither) short-circuit the host pack:
    the fused plan pipeline computes them on device inside its single
    dispatch (:func:`pack_assignment_core`), leaving only the id->name
    gather and list building here.
    """
    assign = np.asarray(assign)
    warnings: dict[str, list[str]] = {}
    P = problem.P
    if (packed is None) != (counts is None):
        raise ValueError("decode_assignment: packed and counts must be "
                         "passed together")

    # Per modeled state with constraints > 0: pack non-empty slots left
    # (stable, preserving slot order), gather names in one shot, and convert
    # to nested Python lists at C speed.
    names_arr = np.asarray(problem.nodes, dtype=object) \
        if problem.nodes else np.zeros(0, dtype=object)
    per_state_rows: dict[int, list[list[str]]] = {}
    per_state_counts: dict[int, np.ndarray] = {}
    for si, sname in enumerate(problem.states):
        want = int(problem.constraints[si])
        if want <= 0:
            continue
        if P == 0 or not problem.nodes:
            # Degenerate: nothing assignable; every slot is a shortfall.
            per_state_rows[si] = [[] for _ in range(P)]
            per_state_counts[si] = np.zeros(P, dtype=np.int64)
            continue
        if packed is not None and counts is not None:
            row_ids = np.asarray(packed)[:, si, :]
            row_counts = np.asarray(counts)[:, si].astype(np.int64)
        else:
            ids = assign[:, si, :]
            mask = ids >= 0
            row_counts = mask.sum(axis=1)
            order = np.argsort(~mask, axis=1, kind="stable")
            row_ids = np.take_along_axis(ids, order, axis=1)
        names = names_arr[np.maximum(row_ids, 0)]
        nested = names.tolist()
        if row_counts.min() == row_ids.shape[1]:  # all slots filled
            per_state_rows[si] = nested
        else:
            per_state_rows[si] = [
                row[:c] for row, c in zip(nested, row_counts.tolist())]
        per_state_counts[si] = row_counts

    # Partitions needing the slow path: source has unmodeled or
    # zero-constraint states to pass through (rare in practice).
    constraints = problem.constraints
    modeled = [
        (si, s) for si, s in enumerate(problem.states)
        if int(constraints[si]) > 0
    ]
    solved_states = {s for _, s in modeled}
    mod_names = [s for _, s in modeled]
    rows_per_state = [per_state_rows[si] for si, _ in modeled]
    removed = nodes_to_remove or []
    native = _marshal.get()
    next_map = None
    if native is not None:
        try:
            next_map = native.build_map(
                Partition, problem.partitions, mod_names, rows_per_state,
                partitions_to_assign, solved_states, set(removed))
        except (TypeError, AttributeError):
            next_map = None  # structural surprise: pure-Python fallback
    if next_map is None:
        next_map = {}
        rows_iter = zip(*rows_per_state) if rows_per_state \
            else (() for _ in range(P))
        get_src = partitions_to_assign.get
        for pname, vals in zip(problem.partitions, rows_iter):
            src = get_src(pname)
            # keys() <= set is a C-level check; the passthrough branch
            # (source carries unmodeled / zero-constraint states) is rare
            # in practice.
            if src is None or src.nodes_by_state.keys() <= solved_states:
                nbs = dict(zip(mod_names, vals))
            else:
                nbs = {}
                for s, ns in src.nodes_by_state.items():
                    if s not in solved_states:
                        nbs[s] = strings_remove(ns, removed)
                for s, v in zip(mod_names, vals):
                    nbs[s] = v
            next_map[pname] = Partition(pname, nbs)

    for si, sname in modeled:
        want = int(constraints[si])
        short = np.nonzero(per_state_counts[si] < want)[0]
        for pi in short:
            pname = problem.partitions[pi]
            warnings.setdefault(pname, []).append(
                "could not meet constraints: %d, stateName: %s,"
                " partitionName: %s" % (want, sname, pname)
            )

    return next_map, warnings
