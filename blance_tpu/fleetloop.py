"""Fleet of control loops: N tenants' continuous rebalance through one
coalesced plan dispatch (ROADMAP item 3 — the production shape).

The paper's deployment (cbgt/FTS at millions of users) is not one
cluster rebalancing once: it is hundreds of tenant *indexes*, each
running its own continuous rebalance loop over a shared node fleet.
PR 7 made many tenants' *solves* one vmapped dispatch
(``plan/fleet.py`` + ``plan/service.py``); PR 10 closed *one* tenant's
loop (``rebalance.RebalanceController``).  This module composes them:

- each tenant runs a full :class:`~blance_tpu.rebalance.
  RebalanceController` — the extracted
  :class:`~blance_tpu.control.CycleEngine` cycle machine — as ONE task
  on a single shared event loop (no thread per tenant);
- every controller plans through a :class:`ServicePlanner`, the
  :class:`~blance_tpu.control.CyclePlanner` that encodes the tenant's
  map problem to dense arrays, submits it to the ONE shared
  :class:`~blance_tpu.plan.service.PlanService`, and decodes the
  result — so tenants whose debounce windows overlap land their
  converge cycles in the SAME bucketed ``[B, ...]`` fleet dispatch
  (GSPMD-style shape bucketing keeps compiled programs shared as
  tenant shapes drift, arXiv:2105.04663).  The steady-state cost of N
  loops is a handful of bucketed programs, not N dispatches;
- per-tenant warm carries ride the service's shared
  :class:`~blance_tpu.plan.carry.CarryCache` under a conservative
  protocol (below) in which a cache eviction or invalidation only ever
  costs a cold solve — never a stale or wrong map;
- the service's ``fair_share`` quota gives cross-tenant admission
  fairness: a chatty tenant churning weight deltas cannot fill a
  coalescing window and starve its neighbors
  (``fleet.starved_admissions``);
- per-tenant SLO accounts aggregate into the fleet-wide
  ``slo.fleet_*`` / ``fleet.*`` scorecard
  (:class:`~blance_tpu.obs.slo.FleetSloRollup`), rendered by the
  existing exposition plane.

Warm-carry protocol (the ServicePlanner side of the CarryCache's
"eviction is always safe" contract): a request states its delta
(``dirty``) — and thereby opts into the one-sweep warm repair — ONLY
when, versus the planner's previous request, (a) the partition set and
every array shape are unchanged, (b) partition AND node weights are
byte-identical (a re-priced problem invalidates the carry, exactly like
``PlannerSession.set_partition_weights``), and (c) the dark-node set
did not shrink (returned capacity must re-balance, which only a cold
solve does).  The dirty mask is then the holders of currently-dark
nodes; the service's value-match of ``prev`` against the cached
assignment catches everything else (superseded passes, failures,
mid-flight divergence) and demotes to cold.  Cold is always correct —
it is the single-problem solve on the current inputs.

Determinism: everything here is loop-only when the service runs
``inline_solve=True`` — under ``testing.sched.DeterministicLoop`` a
multi-hundred-tenant virtual week replays bit-identically
(``testing/fleetsim.py``, docs/SIMULATOR.md).
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import TYPE_CHECKING, Any, Callable, Optional

if TYPE_CHECKING:  # pragma: no cover - type-only (import cycle)
    from .durability.journal import Journal
    from .durability.recover import RecoveredState

import numpy as np

from .control import CyclePlanner
from .core.encode import DenseProblem, decode_assignment, encode_problem
from .core.types import PartitionMap, PartitionModel, PlanOptions
from .obs import get_recorder
from .obs.slo import FleetSloRollup, FleetSloSummary, SloTracker
from .orchestrate.orchestrator import OrchestratorOptions
from .plan.carry import EncodeCache
from .plan.fleet import TenantProblem
from .plan.resident import EncodedState, build_encoded_state
from .plan.service import PlanService
from .rebalance import ClusterDelta, RebalanceController
from .utils.hostclock import perf_now

__all__ = ["FleetController", "ServicePlanner", "TenantLoop"]


class ServicePlanner(CyclePlanner):
    """One tenant's :class:`~blance_tpu.control.CyclePlanner` over the
    shared :class:`~blance_tpu.plan.service.PlanService` (module doc:
    encode → submit → decode, with the conservative warm protocol).

    With ``encode_residency`` (the default) the encode/decode halves
    are DELTA-RESIDENT (:mod:`blance_tpu.plan.resident`): the interned
    problem arrays live in an :class:`~blance_tpu.plan.carry.
    EncodeCache` keyed by tenant, each cycle patches them in O(delta)
    (dark-set flips, weight-row writes, strip scatters), adoption
    replaces ``prev`` with the landed solve's packed assignment, and
    decode patches the held map at the changed rows — a warm converge
    cycle writes only dirty rows + scalars instead of re-running
    ``encode_problem``/``decode_assignment`` over the whole cluster.
    The warm-SOLVE protocol (the ``dirty`` mask, ``_dirty_for``) is
    byte-for-byte the pre-residency decision tree on the resident
    arrays, so solve decisions — and therefore dispatch counts, event
    logs and committed traces — are bit-identical either way; any
    off-protocol event (divergent pass, supersede, statics swap, shape
    drift, cache eviction) demotes to a full re-encode, never a stale
    map.  ``host_phase`` accumulates host wall-clock seconds per phase
    (encode/decode) for the bench stage's phase split."""

    def __init__(self, key: str, service: PlanService, *,
                 recorder: Optional[Any] = None,
                 encode_cache: Optional[EncodeCache] = None,
                 encode_residency: bool = True) -> None:
        self.key = key
        self._service = service
        self._rec = recorder if recorder is not None else get_recorder()
        self._resident = bool(encode_residency)
        self._encodes = encode_cache if encode_cache is not None else (
            EncodeCache(recorder=self._rec) if self._resident else None)
        # Fingerprint of the previous request: (dark set, partition
        # list, prev shape, N, pweights bytes, nweights bytes).  None
        # until the first cycle — the first request is always cold.
        self._last: Optional[tuple[frozenset[str], tuple[str, ...],
                                   tuple[int, ...], int, bytes,
                                   bytes]] = None
        # Host wall-clock per planner phase (perf_counter seconds; NOT
        # recorder/virtual time — the bench phase-split source).
        self.host_phase: dict[str, float] = {"encode": 0.0,
                                             "decode": 0.0}

    async def plan_cycle(
        self,
        current: PartitionMap,
        nodes: list[str],
        removes: list[str],
        model: PartitionModel,
        opts: PlanOptions,
    ) -> tuple[PartitionMap, dict[str, list[str]]]:
        if opts.node_score_booster is not None or \
                opts.node_scorer is not None or \
                opts.node_sorter is not None:
            raise ValueError(
                f"tenant {self.key!r}: the fleet plan service runs the "
                f"dense batch solver, which does not support "
                f"node_score_booster/node_scorer/node_sorter hooks — "
                f"run this tenant on a local planner instead")
        t0 = perf_now()
        problem, st = self._encode(current, nodes, removes, model, opts)
        fp = (frozenset(removes), tuple(problem.partitions),
              tuple(problem.prev.shape), problem.N,
              problem.partition_weights.tobytes(),
              problem.node_weights.tobytes())
        dirty = self._dirty_for(problem, fp)
        tenant = TenantProblem.from_dense(self.key, problem, dirty=dirty)
        self.host_phase["encode"] += perf_now() - t0
        result = await self._service.submit(tenant)
        t1 = perf_now()
        if st is None:
            next_map, warnings = decode_assignment(
                problem, result.assign, current, removes)
            if self._resident:
                self._rec.count("fleet.decode_full")
        else:
            next_map, warnings, full, nrows = st.decode(
                np.asarray(result.assign), current, removes)
            self._rec.count("fleet.decode_full" if full
                            else "fleet.decode_patch")
            if not full:
                self._rec.observe("fleet.decode_dirty_rows",
                                  float(nrows))
        self._last = fp
        self.host_phase["decode"] += perf_now() - t1
        return next_map, warnings

    # -- the encode-residency layer (plan/resident.py) ---------------------

    def _encode(self, current: PartitionMap, nodes: list[str],
                removes: list[str], model: PartitionModel,
                opts: PlanOptions) -> tuple[DenseProblem,
                                            Optional[EncodedState]]:
        """The cycle's encoded problem: the resident arrays patched in
        O(delta) when the warm-encode protocol holds, else a full
        ``encode_problem`` (counted ``fleet.encode_cold``; every such
        cold beyond a tenant's first is preceded by exactly one counted
        demotion or eviction)."""
        if not self._resident:
            return encode_problem(current, current, nodes, removes,
                                  model, opts), None
        assert self._encodes is not None
        rec = self._rec
        st = self._encodes.get(self.key)
        if st is not None:
            reason = self._warm_gate(st, current, nodes, model, opts)
            if reason is None:
                rows = 0
                nbytes = 0
                added = st.apply_nodes(nodes, opts)
                if added is None:
                    self._encodes.invalidate(self.key, "nodes")
                    st = None
                else:
                    nbytes += added[1]
                    rows += st.apply_removes(frozenset(removes))
                    wrows, wbytes = st.apply_weights(opts)
                    rows += wrows
                    nbytes += wbytes
                    rec.count("fleet.encode_warm")
                    if rows:
                        rec.observe("fleet.encode_patch_rows",
                                    float(rows))
                    if nbytes:
                        rec.count("fleet.encode_patch_bytes", nbytes)
                    return st.problem, st
            else:
                self._encodes.invalidate(self.key, reason)
                st = None
        problem = encode_problem(current, current, nodes, removes,
                                 model, opts)
        st = build_encoded_state(problem, current, removes, model, opts)
        if st is not None:
            # Counted only when resident state is actually
            # (re)established: an out-of-protocol tenant (pass-through
            # states, degenerate shapes) full-encodes every cycle by
            # design, and counting those would break the attribution
            # bound (tenants <= encode_cold <= tenants + demotions +
            # evictions) the perf-smoke gate pins.  Its full decodes
            # still show as fleet.decode_full.
            rec.count("fleet.encode_cold")
            self._encodes.put(self.key, st)
        return problem, st

    def _warm_gate(self, st: EncodedState, current: PartitionMap,
                   nodes: list[str], model: PartitionModel,
                   opts: PlanOptions) -> Optional[str]:
        """The conservative protocol: None when the resident state may
        be delta-patched for this cycle, else the demotion reason.  The
        one warm entry besides an adopted pass: ``current`` IS the map
        object this planner returned last cycle (a direct caller
        adopting the proposal wholesale) — then the pending proposal's
        packed assignment is adopted as ``prev`` on the spot."""
        if not st.statics_match(model, opts):
            return "statics"
        if current is not st.expected:
            if st.pending is not None and current is st.pending.map:
                rows, nbytes = st.adopt(st.pending, current)
                self._note_patch(rows, nbytes)
            else:
                return "divergence"
        else:
            p = st.pending
            if p is not None and not p.changed and st.map is None:
                # A zero-move proposal: the solve changed nothing, so
                # its decoded map IS the canonical decode of the
                # unchanged resident prev — holding it unlocks
                # incremental decode without waiting for a pass to
                # land (weight-drift cycles often converge move-free).
                st.map = p.map
            # Any other un-adopted proposal is stale: the cluster
            # stayed on ``expected``, so the next solve re-proposes
            # from the same prev.
            st.pending = None
        if st.shape_drifted():
            return "shape"
        return None

    def _note_patch(self, rows: int, nbytes: int) -> None:
        if rows:
            self._rec.observe("fleet.encode_patch_rows", float(rows))
        if nbytes:
            self._rec.count("fleet.encode_patch_bytes", nbytes)

    # -- controller notifications (rebalance.RebalanceController) ----------

    def notify_strip(self, nodes: set[str], before: PartitionMap,
                     after: PartitionMap) -> None:
        """An abrupt-fail strip replaced the controller's current map:
        patch the resident prev/map at the holder rows and re-key the
        identity token, or demote when the strip did not start from the
        map we encode."""
        if not self._resident:
            return
        assert self._encodes is not None
        st = self._encodes.get(self.key)
        if st is None:
            return
        if st.expected is not before:
            self._encodes.invalidate(self.key, "divergence")
            return
        rows, nbytes = st.apply_strip(nodes, after)
        self._note_patch(rows, nbytes)

    def notify_pass(self, achieved: PartitionMap,
                    end_map: PartitionMap, clean: bool) -> None:
        """An orchestration pass adopted ``achieved`` as current.  When
        the pass landed OUR pending proposal verbatim (``clean`` hint
        from the controller, the target is identical to the proposal
        object, and every row the proposal changed reads back equal),
        adopt: the packed assignment becomes ``prev`` and ``achieved``
        the identity token.  Anything else — supersede, failures,
        quarantine strips, a locally-planned degraded pass — demotes to
        a cold re-encode.  Never a stale map: rows the proposal did not
        change are the held map's own objects, so only changed rows
        need the read-back check."""
        if not self._resident:
            return
        assert self._encodes is not None
        st = self._encodes.get(self.key)
        if st is None:
            return
        p = st.pending
        if not clean or p is None or end_map is not p.map:
            self._encodes.invalidate(self.key, "divergence")
            return
        for pname in p.changed:
            got = achieved.get(pname)
            if got is None or \
                    got.nodes_by_state != p.map[pname].nodes_by_state:
                self._encodes.invalidate(self.key, "divergence")
                return
        rows, nbytes = st.adopt(p, achieved)
        self._note_patch(rows, nbytes)

    def _dirty_for(self, problem: Any,
                   fp: tuple) -> Optional[np.ndarray]:
        """The request's delta mask when the warm path MAY run, else
        None (cold — see the module doc's warm-carry protocol)."""
        last = self._last
        if last is None:
            return None
        dark, parts, shape, n, pw, nw = fp
        ldark, lparts, lshape, ln, lpw, lnw = last
        if parts != lparts or shape != lshape or n != ln:
            return None  # re-shaped problem: any carry is stale
        if pw != lpw or nw != lnw:
            return None  # re-priced problem: the carry's fills lie
        if not (ldark <= dark):
            return None  # capacity returned: only a cold solve rebalances
        dark_ids = np.array(
            [i for i, name in enumerate(problem.nodes) if name in dark],
            np.int32)
        dirty: np.ndarray = np.isin(problem.prev, dark_ids).any(
            axis=(1, 2))
        return dirty


@dataclasses.dataclass
class TenantLoop:
    """One tenant's registered control loop."""

    key: str
    controller: RebalanceController
    planner: ServicePlanner
    slo: SloTracker


class FleetController:
    """N per-tenant rebalance loops multiplexed over one shared plan
    service + carry cache on a single event loop (module doc).

    ``coalesce=False`` is the sequential loop-per-tenant BASELINE: the
    same code path with a zero admission window and ``max_batch=1``,
    so every tenant plan costs its own device dispatch — the
    configuration the ``fleet_loop`` bench stage beats (identical
    final maps, measurably fewer dispatches; docs/FLEET.md).

    Shared state (analysis/race_lint.py SHARED_STATE): the tenant
    registry is mutated only from the driving task (``add_tenant`` /
    ``forget_tenant``), in sync windows; each controller's own state
    follows the CycleEngine discipline; the rollup and the service are
    single-window by their own contracts.
    """

    def __init__(
        self,
        nodes_all: list[str],
        *,
        service: Optional[PlanService] = None,
        coalesce: bool = True,
        admission_window_s: float = 0.002,
        fair_share: Optional[int] = None,
        max_batch: int = 1024,
        max_pending: int = 4096,
        carry_bytes: Optional[int] = 64 << 20,
        carry_entries: Optional[int] = 16384,
        mesh: Optional[Any] = None,
        inline_solve: bool = False,
        batch_floor: int = 16,
        orchestrator_options: Optional[OrchestratorOptions] = None,
        plan_options: Optional[PlanOptions] = None,
        debounce_s: float = 0.05,
        max_passes_per_cycle: int = 8,
        availability_floor: Optional[float] = None,
        recorder: Optional[Any] = None,
        encode_residency: bool = True,
        encode_bytes: Optional[int] = 256 << 20,
        encode_entries: Optional[int] = 16384,
        journal: "Optional[Journal]" = None,
    ) -> None:
        self.nodes_all = list(nodes_all)
        self._rec = recorder if recorder is not None else get_recorder()
        self._own_service = service is None
        if service is None:
            service = PlanService(
                admission_window_s=admission_window_s if coalesce
                else 0.0,
                max_batch=max_batch if coalesce else 1,
                max_pending=max_pending,
                fair_share=fair_share if coalesce else None,
                carry_bytes=carry_bytes,
                carry_entries=carry_entries,
                mesh=mesh,
                inline_solve=inline_solve,
                # Both modes share the floored batch programs: a fleet
                # of loops dispatches many SMALL batches (sequential
                # mode: all B=1), and without the floor every distinct
                # coalesced size compiles its own program.
                batch_floor=batch_floor,
                recorder=self._rec,
            )
        self.service = service
        self.coalesce = coalesce
        self.orch_opts = orchestrator_options or OrchestratorOptions()
        self.plan_options = plan_options
        self.debounce_s = debounce_s
        self.max_passes_per_cycle = max_passes_per_cycle
        self.availability_floor = availability_floor
        self._tenants: dict[str, TenantLoop] = {}
        # Encode residency (docs/DESIGN.md): one shared keyed store of
        # per-tenant resident encode state, the encode-layer sibling of
        # the service's CarryCache — bounded, with eviction only ever
        # costing a cold re-encode.
        self.encode_residency = bool(encode_residency)
        self.encode_cache: Optional[EncodeCache] = EncodeCache(
            max_bytes=encode_bytes, max_entries=encode_entries,
            recorder=self._rec) if self.encode_residency else None
        self.rollup = FleetSloRollup(
            availability_floor, recorder=self._rec,
            clock=self._rec.now)
        # One shared WAL for the whole fleet (docs/DURABILITY.md):
        # every tenant journals through a tenant-tagged view of it,
        # and fleet-tier membership events land untagged — recovery
        # groups records back per tenant.
        self._journal = journal

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        """Start the shared plan service (own-service mode only; a
        caller-supplied service is the caller's lifecycle)."""
        if self._own_service:
            await self.service.start()

    async def stop(self) -> None:
        """Stop every tenant loop, then the shared service (in that
        order: a stopping controller may still await one last plan).

        A tenant engine that died with an exception must not abort the
        wind-down partway (stranding its neighbors' tasks and leaking
        the service's dispatcher/executor): every loop is stopped and
        the service closed first, then the FIRST tenant failure is
        re-raised so the crash still surfaces to the caller."""
        for loop in self._tenants.values():
            loop.controller.stop_soon()
        first_error: Optional[BaseException] = None
        first_key: Optional[str] = None
        for loop in self._tenants.values():
            try:
                await loop.controller.stop()
            except (Exception, asyncio.CancelledError) as exc:
                # CancelledError included: a supervisor that cancelled
                # one engine task must not abort THIS wind-down partway
                # (CancelledError is a BaseException on 3.8+).
                if first_error is None:
                    first_error, first_key = exc, loop.key
        if self._own_service:
            await self.service.stop()
        self.publish_rollup()
        if first_error is not None:
            raise RuntimeError(
                f"tenant {first_key!r} controller died during the "
                f"run") from first_error

    # -- tenants -----------------------------------------------------------

    def add_tenant(
        self,
        key: str,
        model: PartitionModel,
        initial_map: PartitionMap,
        assign_partitions: Callable[..., object],
        *,
        plan_options: Optional[PlanOptions] = None,
        orchestrator_options: Optional[OrchestratorOptions] = None,
        move_observers: tuple = (),
        kick: bool = False,
    ) -> RebalanceController:
        """Onboard one tenant: spawn its controller task on the running
        loop, wire its ServicePlanner + SLO tracker, register it with
        the rollup.  ``kick=True`` submits an empty delta so an
        onboarding tenant (empty placements) converges to a full map
        immediately — the staggered-onboarding entry point."""
        if key in self._tenants:
            raise ValueError(f"tenant {key!r} already registered")
        effective_opts = (plan_options if plan_options is not None
                          else self.plan_options)
        if effective_opts is not None and (
                effective_opts.node_score_booster is not None
                or effective_opts.node_scorer is not None
                or effective_opts.node_sorter is not None):
            # Surface the misconfiguration HERE, where the caller can
            # handle it — inside the engine task it would kill the
            # tenant's loop silently (quiesce still returns, with a
            # stale map) and only resurface at stop().
            raise ValueError(
                f"tenant {key!r}: the fleet plan service runs the dense "
                f"batch solver, which does not support node_score_"
                f"booster/node_scorer/node_sorter hooks — run this "
                f"tenant on a standalone RebalanceController instead")
        top = min((st.priority for st in model.values()), default=0)
        slo = SloTracker(
            initial_map,
            primary_states=[s for s, st in model.items()
                            if st.priority == top],
            clock=self._rec.now, recorder=self._rec,
            track_timeline=True,
            availability_floor=self.availability_floor,
            publish_gauges=False)
        planner = ServicePlanner(
            key, self.service, recorder=self._rec,
            encode_cache=self.encode_cache,
            encode_residency=self.encode_residency)
        if self._journal is not None:
            self._journal.append(
                "fleet", {"event": "add_tenant", "tenant": key},
                t=self._rec.now())
        controller = RebalanceController(
            model, list(self.nodes_all), initial_map, assign_partitions,
            plan_options=(plan_options if plan_options is not None
                          else self.plan_options),
            orchestrator_options=(orchestrator_options
                                  if orchestrator_options is not None
                                  else self.orch_opts),
            backend="greedy",  # degradation-path fallback only
            planner=planner,
            debounce_s=self.debounce_s,
            max_passes_per_cycle=self.max_passes_per_cycle,
            slo=slo, move_observers=move_observers,
            journal=(self._journal.for_tenant(key)
                     if self._journal is not None else None))
        self._tenants[key] = TenantLoop(key, controller, planner, slo)
        self.rollup.register(key, slo)
        controller.start()
        if kick:
            controller.submit(ClusterDelta())
        self.publish_rollup()
        return controller

    def resume_tenant(
        self,
        state: "RecoveredState",
        key: str,
        model: PartitionModel,
        assign_partitions: Callable[..., object],
        *,
        plan_options: Optional[PlanOptions] = None,
        orchestrator_options: Optional[OrchestratorOptions] = None,
        move_observers: tuple = (),
        kick: bool = True,
    ) -> RebalanceController:
        """Re-onboard one tenant from a crashed fleet's recovered
        journal state (docs/DURABILITY.md): same service/planner wiring
        as :meth:`add_tenant`, but the map, membership residue, breaker
        state and SLO horizon come from the journal fold.  The tenant's
        carry/encode residency was never persisted, so its first plan
        is a counted cold solve (``durability.recovery_cold_solves``)
        — inside the fleet tier's demotion attribution bound."""
        from .durability.recover import resume_controller

        if key in self._tenants:
            raise ValueError(f"tenant {key!r} already registered")
        planner = ServicePlanner(
            key, self.service, recorder=self._rec,
            encode_cache=self.encode_cache,
            encode_residency=self.encode_residency)
        controller = resume_controller(
            state, model, assign_partitions, tenant=key,
            plan_options=(plan_options if plan_options is not None
                          else self.plan_options),
            orchestrator_options=(orchestrator_options
                                  if orchestrator_options is not None
                                  else self.orch_opts),
            backend="greedy", planner=planner,
            debounce_s=self.debounce_s,
            max_passes_per_cycle=self.max_passes_per_cycle,
            move_observers=move_observers,
            publish_slo_gauges=False,
            availability_floor=self.availability_floor,
            start=True, kick=kick)
        slo = controller._slo
        assert slo is not None  # resume_controller always restores one
        self._tenants[key] = TenantLoop(key, controller, planner, slo)
        self.rollup.register(key, slo)
        self.publish_rollup()
        return controller

    def forget_tenant(self, key: str) -> None:
        """Drop a tenant's registration (the caller stops its
        controller); its carry-cache entry ages out via the LRU and
        its resident encode state is dropped outright."""
        if key in self._tenants and self._journal is not None:
            self._journal.append(
                "fleet", {"event": "forget_tenant", "tenant": key},
                t=self._rec.now())
        self._tenants.pop(key, None)
        if self.encode_cache is not None:
            self.encode_cache.drop(key)
        self.rollup.forget(key)
        self.publish_rollup()

    def tenant(self, key: str) -> TenantLoop:
        return self._tenants[key]

    def tenants(self) -> list[TenantLoop]:
        return list(self._tenants.values())

    def keys(self) -> list[str]:
        return list(self._tenants)

    # -- delta fan-out -----------------------------------------------------

    def submit(self, key: str, delta: ClusterDelta) -> None:
        """One tenant's delta (weight drift, tenant-local churn)."""
        self._tenants[key].controller.submit(delta)

    def submit_all(self, delta: ClusterDelta) -> None:
        """Fan one cluster-wide membership delta to EVERY tenant loop —
        a correlated zone outage is one event, N coalesced converge
        cycles, a handful of fleet dispatches."""
        for loop in self._tenants.values():
            loop.controller.submit(delta)

    # -- rendezvous & scorecard --------------------------------------------

    async def quiesce_all(self) -> dict[str, PartitionMap]:
        """Wait until every tenant loop is idle; returns each tenant's
        current map (registration order — deterministic under the
        DeterministicLoop)."""
        out: dict[str, PartitionMap] = {}
        for key, loop in self._tenants.items():
            out[key] = await loop.controller.quiesce()
        self.publish_rollup()
        return out

    def publish_rollup(self) -> None:
        """Refresh the fleet-wide gauges (collector-compatible: hand
        this to a ``MetricsServer(collectors=...)``)."""
        self._rec.set_gauge(
            "fleet.converge_cycles",
            float(sum(loop.controller.cycles
                      for loop in self._tenants.values())))
        self.rollup.publish()

    def summary(self) -> FleetSloSummary:
        """The fleet scorecard (per-tenant summaries included)."""
        return self.rollup.summary()

    def host_phases(self) -> dict[str, float]:
        """Cumulative HOST wall-clock seconds per converge-cycle phase
        across every tenant loop: ``encode``/``decode`` from the
        planners, ``device`` from the service's solve worker.  This is
        perf_counter time (not the virtual clock), so it is NOT part of
        the replayable account — it is the bench phase-split source
        that makes the host-encode share visible (docs/FLEET.md)."""
        out = {"encode": 0.0, "decode": 0.0,
               "device": float(self.service.host_solve_s)}
        for loop in self._tenants.values():
            out["encode"] += loop.planner.host_phase["encode"]
            out["decode"] += loop.planner.host_phase["decode"]
        return out

    @property
    def cycles(self) -> int:
        return sum(t.controller.cycles for t in self._tenants.values())

    @property
    def passes(self) -> int:
        return sum(t.controller.passes for t in self._tenants.values())

    @property
    def superseded(self) -> int:
        return sum(t.controller.superseded
                   for t in self._tenants.values())

    @property
    def unconverged_cycles(self) -> int:
        return sum(t.controller.unconverged_cycles
                   for t in self._tenants.values())
