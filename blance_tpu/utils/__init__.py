"""blance_tpu.utils subpackage."""
