"""The injectable host perf-clock seam.

Host-phase timing — solver host milliseconds (``plan.service``), the
fleet planner's encode/decode phases (``fleetloop``), simulator
``wall_s`` (``testing.simulate`` / ``testing.fleetsim``) and
``PhaseTimer`` totals — is *observability about this run of the
program*, not replayed state: none of it may feed a canonical log or
journal, and all of it needs a real wall clock in production.  Instead
of sprinkling ``time.perf_counter()`` through replay-rooted modules
(every call a separate allowlist entry for the determinism lint), those
sites read :func:`perf_now` — ONE declared boundary where wall-clock
enters replay-rooted code (``analysis/determinism.py`` ``CLOCK_SEAMS``).

The default clock is ``time.perf_counter``; tests inject a fake via
:func:`perf_clock` to make host-phase accounting itself deterministic.
The injection point is process-global on purpose: host-phase timing is
diagnostic, a test that wants a frozen clock wants it frozen everywhere,
and the sites it feeds are single-threaded control-plane code.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterator, Optional

__all__ = ["perf_now", "set_perf_clock", "perf_clock"]

_clock: Callable[[], float] = time.perf_counter


def perf_now() -> float:
    """Current host perf-clock reading (seconds; monotonic under the
    default clock).  Differences are host-phase durations."""
    return _clock()


def set_perf_clock(
        clock: Optional[Callable[[], float]]) -> Callable[[], float]:
    """Install ``clock`` as the process perf clock (``None`` restores
    ``time.perf_counter``); returns the previously installed clock."""
    global _clock
    prev = _clock
    _clock = time.perf_counter if clock is None else clock
    return prev


@contextlib.contextmanager
def perf_clock(clock: Callable[[], float]) -> Iterator[None]:
    """Scoped clock injection: install ``clock``, restore on exit."""
    prev = set_perf_clock(clock)
    try:
        yield
    finally:
        set_perf_clock(prev)
