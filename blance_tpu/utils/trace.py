"""Lightweight tracing/profiling for planner and orchestrator phases.

The reference has no tracing (SURVEY.md §5); its observability surface is
the orchestrator progress stream.  Here, in addition to that stream, the
framework exposes:

- ``PhaseTimer``: wall-clock phase timing with a queryable report — kept
  as a thin compatibility shim over ``blance_tpu.obs``: every phase is
  also recorded as a Recorder span (and annotations land on the current
  span), so legacy PhaseTimer callers feed the unified trace for free
  while ``report()`` output stays byte-identical to the pre-obs shape.
- ``device_profile``: context manager around jax.profiler.trace for real
  TPU traces (viewable in TensorBoard / Perfetto), no-op if profiling is
  unavailable.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import Iterator, Optional

from ..obs import get_recorder
from .hostclock import perf_now

__all__ = ["PhaseTimer", "device_profile"]


@dataclass
class PhaseTimer:
    """Accumulates wall-clock per named phase; phases may repeat.

    ``annotations`` carries non-timing facts a caller wants surfaced with
    the timing report — e.g. which score engine the solve actually ran
    after auto-selection/fallback (tensor.solve_converged_resilient)."""

    totals: dict[str, float] = field(default_factory=dict)
    counts: dict[str, int] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = perf_now()
        try:
            with get_recorder().span(name):
                yield
        finally:
            self._accumulate(name, perf_now() - start)

    def _accumulate(self, name: str, elapsed: float) -> None:
        """Fold one elapsed interval into the report totals — the piece of
        the old phase() that is NOT the span; obs.phase_span uses it to
        time a region once while publishing both views."""
        self.totals[name] = self.totals.get(name, 0.0) + elapsed
        self.counts[name] = self.counts.get(name, 0) + 1

    def annotate(self, key: str, value: str) -> None:
        self.annotations[key] = value
        get_recorder().set_attr(key, value)

    def report(self) -> dict[str, dict]:
        out: dict = {
            name: {"total_s": self.totals[name], "count": self.counts[name]}
            for name in self.totals
        }
        if self.annotations:
            out["annotations"] = dict(self.annotations)
        return out

    def __str__(self) -> str:
        parts = [
            f"{name}: {self.totals[name]*1000:.1f}ms x{self.counts[name]}"
            for name in sorted(self.totals, key=self.totals.get, reverse=True)
        ]
        parts += [f"{k}={v}" for k, v in sorted(self.annotations.items())]
        return "; ".join(parts)


@contextlib.contextmanager
def device_profile(log_dir: Optional[str]) -> Iterator[None]:
    """jax.profiler.trace wrapper; inert when log_dir is None or the
    profiler can't start (e.g. no device)."""
    if not log_dir:
        yield
        return
    # Guard only profiler startup — exceptions raised by the caller's body
    # must propagate unchanged (a second yield after throw() would mask
    # them with RuntimeError).
    trace_cm = None
    try:
        import jax

        trace_cm = jax.profiler.trace(log_dir)
        trace_cm.__enter__()
    except (ImportError, RuntimeError, OSError):
        # The documented no-op cases: jax absent, profiler unavailable /
        # already active, log dir unwritable.  Profiling stays
        # best-effort for these.
        trace_cm = None
    except Exception as e:
        # Anything else is unexpected — still best-effort (a profiler
        # bug must not kill the profiled run), but say so instead of
        # silently dropping the trace.
        trace_cm = None
        import warnings

        warnings.warn(
            f"device_profile: unexpected profiler failure "
            f"({type(e).__name__}: {e}); continuing without a device "
            f"trace", RuntimeWarning, stacklevel=3)
    try:
        yield
    finally:
        if trace_cm is not None:
            trace_cm.__exit__(None, None, None)
