"""Shared compile-and-cache helper for the repo's native components.

Both native loaders — the ctypes planner core (plan/native.py) and the
CPython marshalling extension (core/marshal.py) — need the same shape:
compile the source once, cache the .so next to the package, rebuild when
the source is newer, and never hard-fail when the toolchain is missing.
"""

from __future__ import annotations

import os
import subprocess

__all__ = ["compile_cached"]


def compile_cached(source: str, out_path: str, command: list[str]) -> bool:
    """Ensure ``out_path`` exists and is newer than ``source``.

    ``command`` is the full compiler invocation (it should reference
    ``source`` and ``out_path``).  Returns True when a fresh-enough binary
    is in place; False when the source is missing or the build failed —
    callers fall back to their pure-Python paths.
    """
    if not os.path.exists(source):
        return False
    try:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        if (not os.path.exists(out_path)
                or os.path.getmtime(out_path) < os.path.getmtime(source)):
            subprocess.run(command, check=True, capture_output=True)
        return True
    except (OSError, subprocess.CalledProcessError):
        return False
