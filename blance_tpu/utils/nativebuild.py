"""Shared compile-and-cache helper for the repo's native components.

Both native loaders — the ctypes planner core (plan/native.py) and the
CPython marshalling extension (core/marshal.py) — need the same shape:
compile the source once, cache the .so next to the package, rebuild when
the source is newer, and never hard-fail when the toolchain is missing.
"""

from __future__ import annotations

import os
import subprocess

__all__ = ["compile_cached"]


def compile_cached(source: str, out_path: str, command: list[str]) -> bool:
    """Ensure ``out_path`` exists and is newer than ``source``.

    ``command`` is the full compiler invocation (it should reference
    ``source`` and ``out_path``).  Returns True when a fresh-enough binary
    is in place; False when the source is missing or the build failed —
    callers fall back to their pure-Python paths.

    The compiler writes to a process-unique temp path in the same
    directory, published with an atomic os.replace(): concurrent importers
    only ever dlopen a fully-written shared object (a plain in-place write
    passes the existence/mtime check the moment the file is created).
    """
    if not os.path.exists(source):
        return False
    tmp_path = f"{out_path}.{os.getpid()}.tmp"
    try:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        if (not os.path.exists(out_path)
                or os.path.getmtime(out_path) < os.path.getmtime(source)):
            subprocess.run(
                [tmp_path if c == out_path else c for c in command],
                check=True, capture_output=True)
            os.replace(tmp_path, out_path)
        return True
    except (OSError, subprocess.CalledProcessError):
        return False
    finally:
        if os.path.exists(tmp_path):
            try:
                os.remove(tmp_path)
            except OSError:
                pass
