"""Crash-atomic filesystem writes — the ONE copy of the recipe.

Three persistence paths grew the same temp+fsync+rename idiom
independently (``rebalance.save_partition_map``, ``CostModel.save``,
and the durability journal's segment rotation), and all three shared
the same latent hole: the FILE is fsync'd, but the containing
DIRECTORY is not, so on power failure the rename itself — the step
that makes the new bytes visible under the real name — can be lost
and the checkpoint silently reverts.  POSIX only guarantees the
directory entry is durable after an fsync on the *directory* fd.

This module is that recipe, once, with the hole fixed:

1. temp file IN THE SAME DIRECTORY (``os.replace`` is only atomic
   within a filesystem),
2. optional mode preservation (mkstemp creates 0600, which would break
   unprivileged readers of a world-readable checkpoint),
3. write + flush + ``os.fsync`` on the file,
4. ``os.replace`` into place,
5. ``os.fsync`` on the directory fd so the rename is durable too,
6. unlink-the-temp + re-raise on any failure — the previous file
   survives untouched.

fsync (steps 3 and 5) is gated by the ``BLANCE_WAL_FSYNC`` env var
(default ON; set ``0`` to skip) so CI and tests that hammer the
journal do not pay thousands of real disk barriers.  Atomicity (temp +
rename) is NOT gated — only durability-across-power-loss is.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Optional

__all__ = [
    "fsync_enabled",
    "fsync_dir",
    "atomic_write_text",
    "atomic_write_json",
]

_FSYNC_ENV = "BLANCE_WAL_FSYNC"


def fsync_enabled() -> bool:
    """True unless ``BLANCE_WAL_FSYNC=0`` — the CI speed valve."""
    return os.environ.get(_FSYNC_ENV, "1") != "0"


def fsync_dir(directory: str) -> None:
    """Make a completed rename in ``directory`` durable.

    No-op when fsync is gated off, or on platforms where a directory
    cannot be opened/fsync'd (Windows raises; some network filesystems
    return EINVAL) — there the rename is still atomic, just not
    guaranteed to survive power loss, which matches the old behavior.
    """
    if not fsync_enabled():
        return
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _target_mode(path: str) -> int:
    """Mode to stamp on the temp file: the existing target's, or the
    umask default for a fresh file (never mkstemp's 0600)."""
    try:
        return os.stat(path).st_mode & 0o777
    except FileNotFoundError:
        umask = os.umask(0)
        os.umask(umask)
        return 0o666 & ~umask


def atomic_write_text(path: str, text: str, *,
                      preserve_mode: bool = True) -> None:
    """Atomically (and, fsync permitting, durably) replace ``path``
    with ``text``.  See the module docstring for the exact recipe."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp", dir=directory)
    try:
        if preserve_mode:
            os.fchmod(fd, _target_mode(path))
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            if fsync_enabled():
                os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(directory)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj: Any, *,
                      indent: Optional[int] = None,
                      sort_keys: bool = False,
                      preserve_mode: bool = True) -> None:
    """``atomic_write_text`` with JSON encoding (same output bytes as a
    direct ``json.dump`` with the same knobs)."""
    atomic_write_text(
        path, json.dumps(obj, indent=indent, sort_keys=sort_keys),
        preserve_mode=preserve_mode)
