"""Placement-control hooks: booster, custom scorer, full custom sorter.

The reference exposes three escalating control points as package globals
(NodeScoreBooster, CustomNodeSorter — reference plan.go:566-580,693-697);
here they are per-call PlanOptions fields:

  1. node_score_booster + negative node weights — steer NEW load away
     from nodes being drained/protected, without moving what's there
     (the couchbase/cbgt pattern, control_test.go:19-29).
  2. node_scorer — replace the score formula; the framework keeps the
     deterministic node-position tie-break.
  3. node_sorter — replace the ENTIRE candidate ordering, tie-break
     policy included.

Each hook runs on the exact planner; `backend="auto"`/"tpu" route hooked
plans to the exact path automatically (a Python callable can't run
inside the jitted batch solver) — EXCEPT the cbgt booster, whose shape
is baked into the device score, so boosted plans stay on the fast path.

Run:  python examples/custom_policy.py   (JAX_PLATFORMS=cpu works too)
"""

import collections
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Some TPU runtime plugins override JAX_PLATFORMS from the
    # environment; pin through the config API so the documented
    # "set JAX_PLATFORMS=cpu" invocation is honored everywhere.
    import jax

    jax.config.update("jax_platforms", "cpu")

import blance_tpu as bt
from blance_tpu.plan.greedy import default_node_score
from blance_tpu.plan.native import cbgt_node_score_booster

MODEL = bt.model(primary=(0, 1), replica=(1, 1))
NODES = ["a", "b", "c", "d"]


def loads(pmap):
    out = collections.Counter()
    for p in pmap.values():
        for ns in p.nodes_by_state.values():
            for n in ns:
                out[n] += 1
    return dict(sorted(out.items()))


def fresh(n=32):
    return {str(i): bt.Partition(str(i), {}) for i in range(n)}


def main():
    parts = fresh()

    # 1. Booster: steer NEW load away from node d (weight -2).  The
    #    boost is a fixed score offset (max(-w, stickiness)), NOT a hard
    #    exclusion — once other nodes carry enough copies the count
    #    pressure overrides it, exactly like the reference — so the
    #    steering demo uses few partitions (the reference's control
    #    tests use 1-3, control_test.go:18-416).
    few = fresh(4)
    drained, _ = bt.plan_next_map(
        few, few, NODES, [], NODES, MODEL,
        bt.PlanOptions(node_weights={"d": -2},
                       node_score_booster=cbgt_node_score_booster),
        backend="auto")
    print("booster (steer new load off d):", loads(drained))
    assert loads(drained).get("d", 0) == 0

    # 2. Custom scorer: bias primaries toward node c by 2 score units
    #    (score ~ held count, so c settles ~2 primaries above the rest);
    #    ties still break by node position, so the plan stays
    #    deterministic.
    def prefer_c(ctx, node):
        r = default_node_score(ctx, node)
        return r - 2.0 if (node == "c" and ctx.state_name == "primary") \
            else r

    biased, _ = bt.plan_next_map(
        parts, parts, NODES, [], NODES, MODEL,
        bt.PlanOptions(node_scorer=prefer_c), backend="auto")
    prim = collections.Counter(
        p.nodes_by_state["primary"][0] for p in biased.values())
    print("scorer (bias primaries toward c):", dict(sorted(prim.items())))
    assert prim["c"] > max(v for k, v in prim.items() if k != "c")

    # 3. Full sorter: reverse the tie-break policy (last node wins ties)
    #    — something node_scorer cannot express.
    def reverse_ties(ctx, nodes):
        return sorted(nodes, key=lambda n: (default_node_score(ctx, n),
                                            -ctx.node_positions.get(n, 0)))

    rev, _ = bt.plan_next_map(
        parts, parts, NODES, [], NODES, MODEL,
        bt.PlanOptions(node_sorter=reverse_ties), backend="auto")
    first = rev["0"].nodes_by_state["primary"]
    print("sorter (reversed ties): partition 0 primary ->", first)
    assert first == ["d"]

    print("OK")


if __name__ == "__main__":
    main()
