"""Fleet-scale multi-tenant replanning through the plan service.

A cbgt/FTS-style deployment rebalances ~100 tenant indexes at once —
each a small independent plan.  Solved one at a time, that is ~100
device dispatches; the fleet tier groups the tenants into shape-bucket
batch classes, stacks each class into one [B, P, S, N] problem tensor,
and vmaps the dense solver over the batch (plan/fleet.py), fronted by
an asyncio plan service with request coalescing and a per-tenant
warm-carry cache (plan/service.py).  This script drives two fleet
rounds — a cold fleet-wide replan, then a node-outage delta round that
rides the carry cache warm — printing batch occupancy, admission
latency, and the speedup vs the sequential per-tenant loop.

Run:  python examples/fleet_replan.py   [TENANTS]
(default 100; use JAX_PLATFORMS=cpu off-TPU — multi-device hosts shard
the batch axis over the mesh automatically)
"""

import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Some TPU runtime plugins override JAX_PLATFORMS from the
    # environment; pin through the config API so the documented
    # "use JAX_PLATFORMS=cpu" invocation is honored everywhere.
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import jax
import jax.numpy as jnp

from blance_tpu.core.encode import pad_problem_arrays
from blance_tpu.obs import Recorder, use_recorder
from blance_tpu.parallel.sharded import make_mesh
from blance_tpu.plan.fleet import TenantProblem, batch_class_of
from blance_tpu.plan.service import PlanService
from blance_tpu.plan.tensor import (
    resolve_default_fused_score,
    solve_converged_resilient,
)


def make_tenant(i):
    """One tenant index: mixed sizes (17..24 partitions) spread across
    four shape-bucket classes, rack rules on."""
    rng = np.random.default_rng(7_000 + i)
    P = int(rng.integers(17, 25))
    N = 8
    prev = np.full((P, 2, 1), -1, np.int32)
    prev[:, 0, 0] = rng.integers(0, N, P)
    prev[:, 1, 0] = (prev[:, 0, 0] + 1 + rng.integers(0, N - 1, P)) % N
    return TenantProblem(
        key=f"index-{i:03d}", prev=prev,
        partition_weights=np.ones(P, np.float32),
        node_weights=np.ones(N, np.float32),
        valid_node=np.ones(N, bool),
        stickiness=np.full((P, 2), 1.5, np.float32),
        gids=np.stack([np.arange(N, dtype=np.int32),
                       np.arange(N, dtype=np.int32) // 4,
                       np.zeros(N, np.int32)]),
        gid_valid=np.ones((3, N), bool),
        constraints=(1, 1), rules=((), ((2, 1),)))


def solve_sequential(t):
    """The single-problem path a fleet replan runs today: one bucketed
    device dispatch per tenant."""
    k = batch_class_of(t)
    arrs = pad_problem_arrays(
        t.prev, t.partition_weights, t.node_weights, t.valid_node,
        t.stickiness, t.gids, t.gid_valid, k.p, k.n)
    out, _ = solve_converged_resilient(
        *[jnp.asarray(a) for a in arrs], t.constraints, t.rules,
        max_iterations=10, mode=resolve_default_fused_score(k.p, k.n),
        allow_fallback=False, context="fleet_replan.sequential",
        p_real=jax.device_put(np.float32(t.prev.shape[0])))
    return np.asarray(out)[:t.prev.shape[0]]


async def main():
    n_tenants = int(sys.argv[1]) if len(sys.argv) > 1 else 100
    tenants = [make_tenant(i) for i in range(n_tenants)]
    classes = sorted({(k.p, k.n) for k in map(batch_class_of, tenants)})
    print(f"{n_tenants} tenant indexes in {len(classes)} bucket "
          f"classes: {['%dx%d' % c for c in classes]}")

    n_dev = len(jax.devices())
    if jax.default_backend() == "cpu":
        n_dev = min(n_dev, os.cpu_count() or 1)
    mesh = make_mesh(n_dev) if n_dev > 1 else None
    def outage_round(base, results):
        """Delta requests: one held node dies per tenant; each request
        states its delta (dirty mask) so the service's carry cache can
        ride the one-sweep warm repair."""
        reqs = []
        for t, r in zip(base, results):
            victim = int(np.unique(r.assign[r.assign >= 0])[0])
            valid2 = t.valid_node.copy()
            valid2[victim] = False
            reqs.append(TenantProblem(
                key=t.key, prev=r.assign,
                partition_weights=t.partition_weights,
                node_weights=t.node_weights, valid_node=valid2,
                stickiness=t.stickiness, gids=t.gids,
                gid_valid=t.gid_valid, constraints=t.constraints,
                rules=t.rules,
                dirty=(r.assign == victim).any(axis=(1, 2))))
        return reqs

    rec = Recorder()
    with use_recorder(rec):
        svc = PlanService(admission_window_s=0.005, mesh=mesh,
                          max_pending=n_tenants, recorder=rec)
        await svc.start()

        # Warm-up pass: one cold + one warm round compiles each bucket
        # class's batch programs (batch sizes bucket too, so the timed
        # rounds below reuse these compiles), then the timed rounds
        # measure steady-state service throughput.
        t0 = time.perf_counter()
        w1 = await asyncio.gather(*[svc.submit(t) for t in tenants])
        await asyncio.gather(
            *[svc.submit(t) for t in outage_round(tenants, w1)])
        print(f"warm-up (jit compiles, cold + warm programs per class): "
              f"{time.perf_counter() - t0:.1f}s")

        # Round 1 — fleet-wide cold replan: every tenant coalesces into
        # one batch per bucket class.
        t0 = time.perf_counter()
        round1 = await asyncio.gather(*[svc.submit(t) for t in tenants])
        fleet_s = time.perf_counter() - t0

        # Round 2 — a node outage touches every tenant; the requests
        # reuse round 1's cached carries and ride the warm repair.
        t0 = time.perf_counter()
        round2 = await asyncio.gather(
            *[svc.submit(t) for t in outage_round(tenants, round1)])
        delta_s = time.perf_counter() - t0
        await svc.stop()

    # Sequential baseline (one compile warm-up per class, same backend,
    # same padded shapes).
    seen = set()
    for t in tenants:
        if batch_class_of(t) not in seen:
            seen.add(batch_class_of(t))
            solve_sequential(t)
    t0 = time.perf_counter()
    seq_outs = [solve_sequential(t) for t in tenants]
    seq_s = time.perf_counter() - t0

    identical = all(np.array_equal(a, r.assign)
                    for a, r in zip(seq_outs, round1))
    warm = sum(r.warm for r in round2)
    occ = rec.histogram_summary("fleet.batch_tenants")
    lat = rec.histogram_summary("fleet.admission_latency_s")
    print(f"round 1 (cold fleet replan): {fleet_s * 1000:.0f}ms for "
          f"{n_tenants} tenants ({n_tenants / fleet_s:.0f} solves/s), "
          f"bit-identical to the sequential loop: {identical}")
    print(f"round 2 (node-outage delta): {delta_s * 1000:.0f}ms, "
          f"{warm}/{n_tenants} tenants rode the warm carry cache")
    print(f"sequential loop: {seq_s * 1000:.0f}ms "
          f"({n_tenants / seq_s:.0f} solves/s)  ->  fleet speedup "
          f"{seq_s / fleet_s:.1f}x")
    print(f"batch occupancy: mean {occ['sum'] / occ['count']:.1f} "
          f"tenants/dispatch (max {occ['max']:.0f}); admission latency "
          f"p50 {lat['p50'] * 1000:.1f}ms (p95 "
          f"{lat['p95'] * 1000:.1f}ms — includes the warm-up rounds' "
          f"compiles)")


if __name__ == "__main__":
    asyncio.run(main())
