"""Continuous cluster life: the closed-loop simulator scenario pack.

The other examples run ONE rebalance; this one runs a cluster's *life*:
a RebalanceController absorbing a scripted week of churn — spot
preemptions, zone flaps, hot-tenant weight drift, joins and graceful
decommissions — entirely under the DeterministicLoop virtual clock, so
the whole thing replays bit-identically in about a second of wall time.

    python examples/continuous_cluster.py            # the scenario pack
    python examples/continuous_cluster.py --live     # + a live controller demo

Docs: docs/SIMULATOR.md (scenario DSL, determinism contract, event-log
schema, replay workflow).
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from blance_tpu import model
from blance_tpu.core.types import Partition
from blance_tpu.rebalance import ClusterDelta, RebalanceController
from blance_tpu.testing.scenarios import SCENARIOS
from blance_tpu.testing.simulate import run_scenario


def pct(lags, q):
    lags = sorted(lags)
    return lags[min(int(q * len(lags)), len(lags) - 1)] if lags else None


def scenario_pack():
    """Run every registered scenario family at its documented seed and
    print the horizon scorecard."""
    print(f"{'scenario':16s} {'deltas':>6s} {'passes':>6s} {'sprsd':>5s} "
          f"{'tw-avail':>9s} {'churn':>6s} {'lag p50/p95':>12s} "
          f"{'sim-s/wall-s':>12s}")
    for name, build in SCENARIOS.items():
        scn = build(11)
        if name == "mixed_week":
            scn = SCENARIOS[name](11)  # the full 7-day soak
        r = run_scenario(scn)
        churn = (f"{r.churn_vs_offline:.2f}"
                 if r.churn_vs_offline is not None else "—")
        print(f"{name:16s} {r.deltas:6d} {r.rebalances:6d} "
              f"{r.superseded:5d} "
              f"{r.summary.time_weighted_availability:9.5f} {churn:>6s} "
              f"{pct(r.convergence_lags, .5):5.1f}/"
              f"{pct(r.convergence_lags, .95):<5.1f}s "
              f"{r.horizon_s / max(r.wall_s, 1e-9):11.0f}x")
        assert r.complete and not r.unscripted_drops
    print("\nEvery run is a pure function of its seed: re-running "
          "reproduces the event log, SLO summary and exposition text "
          "byte-for-byte (tests/test_simulate.py pins it).")


async def live_demo():
    """Drive a RebalanceController by hand on the real asyncio loop —
    the same control surface the simulator scripts."""
    m = model(primary=(0, 1), replica=(1, 1))
    nodes = [f"n{i}" for i in range(6)]
    current = {
        f"p{i:02d}": Partition(f"p{i:02d}", {
            "primary": [nodes[i % 6]],
            "replica": [nodes[(i + 1) % 6]]})
        for i in range(24)
    }

    async def assign(stop_ch, node, partitions, states, ops):
        await asyncio.sleep(0.001)  # your data plane goes here

    ctl = RebalanceController(m, nodes, current, assign, debounce_s=0.02)
    ctl.start()

    print("\nlive: decommissioning n0 ...")
    ctl.submit(ClusterDelta(remove=("n0",)))
    await ctl.quiesce()
    print(f"live: converged in {ctl.passes} pass(es)")

    print("live: spot-preempting n1+n2 while a weight wave lands ...")
    ctl.submit(ClusterDelta(fail=("n1", "n2")))
    ctl.submit(ClusterDelta(partition_weights={"p00": 8, "p01": 8}))
    final = await ctl.quiesce()
    await ctl.stop()
    survivors = {n for p in final.values()
                 for ns in p.nodes_by_state.values() for n in ns}
    print(f"live: serving from {sorted(survivors)}; "
          f"superseded={ctl.superseded} degraded={len(ctl.degraded_reports)}")


def main():
    scenario_pack()
    if "--live" in sys.argv:
        asyncio.run(live_demo())


if __name__ == "__main__":
    main()
