"""End-to-end cluster lifecycle with blance_tpu.

A vbucket-style scenario (1024 partitions, primary + 1 replica, two
racks) driven the way couchbase/cbgt drives the reference library:

  1. fresh cluster  -> plan a balanced, rack-aware map
  2. execute the transition with the orchestrator (fake data plane here)
  3. a node dies    -> replan from the current map, orchestrate the delta
  4. cluster grows  -> replan, watch load migrate onto the new nodes

Run:  python examples/cluster_rebalance.py        (any backend machine;
set JAX_PLATFORMS=cpu to force the CPU platform)
"""

import asyncio
import collections
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Some TPU runtime plugins override JAX_PLATFORMS from the
    # environment; pin through the config API so the documented
    # "set JAX_PLATFORMS=cpu" invocation is honored everywhere.
    import jax

    jax.config.update("jax_platforms", "cpu")

import blance_tpu as bt
from blance_tpu.orchestrate import OrchestratorOptions, orchestrate_moves


MODEL = bt.model(primary=(0, 1), replica=(1, 1))
P = 1024


def racked(nodes):
    """node -> rack -> zone containment for HierarchyRules."""
    hier = {n: f"rack{i % 2}" for i, n in enumerate(nodes)}
    hier.update({"rack0": "dc", "rack1": "dc"})
    return bt.PlanOptions(
        node_hierarchy=hier,
        # Replicas on a different rack than the primary.
        hierarchy_rules={"replica": [bt.HierarchyRule(2, 1)]},
    )


def load_report(pmap, nodes):
    loads = collections.Counter()
    for p in pmap.values():
        for ns in p.nodes_by_state.values():
            loads.update(ns)
    return {n: loads.get(n, 0) for n in nodes}


async def execute(beg_map, end_map, nodes, label):
    """Drive the orchestrator with an in-memory 'data plane'."""
    ops_done = collections.Counter()

    def assign_partitions(stop_ch, node, partitions, states, ops):
        # Real systems move data here (backfill, promote, ...) and block
        # until durable; raising or returning an Exception fails the move.
        for op in ops:
            ops_done[op] += 1

    o = orchestrate_moves(
        MODEL,
        OrchestratorOptions(
            max_concurrent_partition_moves_per_node=4,
            # Throughput mode: fine for big deltas; flip to True for the
            # reference's freshest-choice scheduling.
            interrupt_on_first_feed=False,
            device_diff=True,  # whole-map diff on device
        ),
        nodes, beg_map, end_map, assign_partitions)

    last = None
    async for progress in o.progress_ch():  # MUST drain until close
        last = progress
    o.stop()
    assert not last.errors, last.errors
    print(f"  {label}: ops {dict(ops_done)}, "
          f"moves ok {last.tot_mover_assign_partition_ok}")
    return last


def main():
    nodes = [f"n{i}" for i in range(8)]
    opts = racked(nodes)
    empty = {str(i): bt.Partition(str(i), {}) for i in range(P)}

    # 1. Fresh, balanced, rack-aware plan (auto -> TPU for big problems).
    m1, warnings = bt.plan_next_map(
        empty, empty, nodes, [], nodes, MODEL, opts, backend="auto")
    assert not warnings
    print("fresh plan loads:", load_report(m1, nodes))

    # 2. Execute the initial build-out.
    asyncio.run(execute(empty, m1, nodes, "build-out"))

    # 3. Node n3 dies. Replan from current map; only displaced copies move.
    m2, _ = bt.plan_next_map(m1, m1, nodes, ["n3"], [], MODEL, opts,
                             backend="auto")
    moves = sum(
        m1[p].nodes_by_state != m2[p].nodes_by_state for p in m1)
    print(f"after losing n3: {moves} partitions touched, loads:",
          load_report(m2, nodes))
    asyncio.run(execute(m1, m2, nodes, "failover rebalance"))

    # 4. Two nodes join; load migrates onto them (and nowhere else than
    #    necessary).
    grown = nodes + ["n8", "n9"]
    m3, _ = bt.plan_next_map(m2, m2, grown, ["n3"], ["n8", "n9"], MODEL,
                             racked(grown), backend="auto")
    print("after growth loads:", load_report(m3, grown))
    asyncio.run(execute(m2, m3, grown, "growth rebalance"))

    # Checkpoint: the map itself is the durable state.
    bt.save_partition_map(m3, "/tmp/cluster_map.json")
    restored = bt.load_partition_map("/tmp/cluster_map.json")
    assert {k: v.nodes_by_state for k, v in restored.items()} == \
        {k: v.nodes_by_state for k, v in m3.items()}
    print("checkpoint round-trip OK")


if __name__ == "__main__":
    main()
