"""Steady-state planning at scale with PlannerSession.

One-shot ``plan_next_map`` re-interns every name on every call; a
long-lived cluster controller should hold a session instead — interning
tables, the compiled solver, and the current dense assignment persist, so
each rebalance is: mutate membership, solve on device, diff on device,
apply.  PartitionMaps materialize only for checkpoints.

Run:  python examples/dense_session_loop.py   [P] [N]
(defaults 20000 x 500; use JAX_PLATFORMS=cpu off-TPU)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # Some TPU runtime plugins override JAX_PLATFORMS from the
    # environment; pin through the config API so the documented
    # "use JAX_PLATFORMS=cpu" invocation is honored everywhere.
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import blance_tpu as bt
from blance_tpu.moves.batch import OP_NAMES
from blance_tpu.plan.tensor import check_assignment


def main():
    P = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000
    N = int(sys.argv[2]) if len(sys.argv) > 2 else 500

    model = bt.model(primary=(0, 1), replica=(1, 1))
    nodes = [f"node-{i:04d}" for i in range(N)]
    partitions = [str(i) for i in range(P)]

    session = bt.PlannerSession(model, nodes, partitions)

    t0 = time.perf_counter()
    session.replan()
    session.apply()
    print(f"initial plan of {P}x{N}: {time.perf_counter() - t0:.2f}s "
          f"(includes jit compile)")

    # A rolling maintenance window: drain 2% of nodes, replan, apply,
    # re-add them, five times — the steady-state controller loop.
    drained = [nodes[i::50][0] for i in range(5)]
    for step, victim in enumerate(drained):
        t0 = time.perf_counter()
        session.remove_nodes([victim])
        session.replan()
        mv_nodes, mv_states, mv_ops = session.moves()
        n_ops = int((mv_ops >= 0).sum())
        session.apply()
        session.add_nodes([victim])  # back in service for the next replan
        dt = time.perf_counter() - t0
        ops = {name: int((mv_ops == i).sum())
               for i, name in enumerate(OP_NAMES) if (mv_ops == i).any()}
        print(f"  drain {victim}: {n_ops} ops {ops} in {dt*1000:.0f}ms")

    # The last victim is back in service but empty — one final replan
    # restores it (only the copies it should carry move back).
    session.replan()
    session.apply()

    report = check_assignment(session.problem, session.current)
    assert report == {"duplicates": 0, "on_removed_nodes": 0,
                      "unfilled_feasible_slots": 0,
                      "hierarchy_misses": 0}, report
    counts = np.bincount(session.current[session.current >= 0], minlength=N)
    print(f"final spread: {counts.max() - counts.min()} "
          f"(ideal per-node load {2 * P // N})")

    # Checkpoint only at the edge.
    final_map, warnings = session.to_map()
    assert not warnings
    bt.save_partition_map(final_map, "/tmp/dense_session_map.json")
    print("checkpointed to /tmp/dense_session_map.json")


if __name__ == "__main__":
    main()
